"""Fused SPMD training: forward + backward + gradient reduction + optimizer
update as ONE jitted XLA program over a device mesh.

This is the TPU-native replacement for the reference's entire hot training
path (SURVEY.md §3.2): Gluon's eager fwd/bwd + `Trainer._allreduce_grads`
(KVStore push/pull over NCCL/ps-lite) + per-param optimizer ops collapse
into a single compiled step. Gradient reduction needs no explicit psum —
parameters are replicated (or FSDP-sharded) and the batch is sharded over
the ``dp`` axis, so XLA inserts the all-reduce/reduce-scatter on ICI/DCN
itself and overlaps it with the backward pass (the reference's P3 priority
propagation, compiler-scheduled — SURVEY.md §2.3).

Sharding modes:
  - ``replicated``: pure data parallelism (reference kvstore=`device`/`nccl`)
  - ``fsdp``: parameters/optimizer state sharded over the ``fsdp`` axis
    (ZeRO-style; beyond reference capability but idiomatic on TPU)
  - per-Parameter ``PartitionSpec`` hints (``Parameter._sharding``) override
    both — used by models/ for tensor parallelism.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import autograd, random as _random
from ..base import MXNetError, getenv_bool
from ..ndarray import NDArray
from ..optimizer import create as opt_create
from ..train.outcomes import StepOutcome, StepRecorder
from . import mesh as _mesh

__all__ = ["SPMDTrainer", "shard_params", "replicate", "constrain",
           "activation_sharding_scope"]

# Mesh active while SPMDTrainer traces the fused step — models call
# ``constrain`` on activations against it (a no-op everywhere else).
_ACTIVE_MESH: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("mxtpu_active_mesh", default=None)


@contextlib.contextmanager
def activation_sharding_scope(mesh: Mesh):
    tok = _ACTIVE_MESH.set(mesh)
    try:
        yield
    finally:
        _ACTIVE_MESH.reset(tok)


def constrain(x, *spec, mesh: Optional[Mesh] = None):
    """Pin an activation's sharding inside the fused SPMD step
    (``lax.with_sharding_constraint`` against the trainer's mesh, or an
    explicitly passed ``mesh``).

    Models sprinkle this on attention/FFN activations so the partitioner
    never falls back to replicate-then-repartition between fsdp-placed
    and tp-hinted params (VERDICT r2 weak #3). Each ``spec`` entry is an
    axis name, a tuple of axis names, or None; axes absent from the mesh
    or of size 1 are dropped, and with no mesh (active or given) the
    call returns ``x`` unchanged — so model code is mesh-agnostic.

    NOTE: a combined batch entry over {dp, fsdp} is CANONICALIZED to
    ``("fsdp", "dp")`` regardless of the order the caller wrote — the
    batch dim is semantically "sharded over both", and fsdp-major is the
    natural tile order of every fsdp-derived NamedSharding, so a single
    canonical order here keeps batch constraints permutation-compatible
    with the fsdp all-gather (the dp>=4 full-remat fix, PERF_NOTES
    round 6). Callers needing dp-major tiles for this axis pair must
    call ``lax.with_sharding_constraint`` directly."""
    if mesh is None:
        mesh = _ACTIVE_MESH.get()
    if mesh is None:
        return x
    entries = []
    for e in spec:
        axes = tuple(e) if isinstance(e, (tuple, list)) else \
            ((e,) if e is not None else ())
        kept = tuple(a for a in axes
                     if a in mesh.shape and mesh.shape[a] > 1)
        if set(kept) == {"dp", "fsdp"}:
            kept = ("fsdp", "dp")
        entries.append(kept if len(kept) > 1 else
                       (kept[0] if kept else None))
    if all(e is None for e in entries):
        return x
    is_nd = isinstance(x, NDArray)
    val = x._data if is_nd else x
    entries += [None] * (val.ndim - len(entries))
    out = jax.lax.with_sharding_constraint(
        val, NamedSharding(mesh, PartitionSpec(*entries)))
    return NDArray(out) if is_nd else out


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def _fsdp_spec(shape, mesh: Mesh,
               base: Optional[PartitionSpec] = None) -> PartitionSpec:
    """Shard the largest divisible still-unsharded dim over the fsdp axis.

    ``base`` (e.g. a tp hint from the model) is preserved: fsdp extends it
    on a free dim instead of fighting it — keeping param layouts
    consistent so the partitioner never reshards activations between
    tp-hinted and fsdp-placed params (VERDICT r2 weak #3)."""
    import os
    size = mesh.shape["fsdp"]
    entries = list(base) if base is not None else []
    entries += [None] * (len(shape) - len(entries))
    n_elems = 1
    for s in shape:
        n_elems *= s
    min_elems = int(os.environ.get("MXTPU_FSDP_MIN_SIZE", "16384"))
    if size == 1 or (base is None and (len(shape) < 2
                                       or n_elems < min_elems)):
        # rank-1 params (biases, layernorm scales) and small tensors stay
        # replicated: the bytes saved are trivial and sharding them forces
        # the partitioner to reshard every activation that touches them
        return PartitionSpec(*entries) if base is not None \
            else PartitionSpec()
    used = {a for e in entries if e is not None
            for a in (e if isinstance(e, tuple) else (e,))}
    if "fsdp" in used:
        return PartitionSpec(*entries)
    # prefer EARLIER dims (vocab for embeddings, out-features for Dense):
    # sharding a trailing feature dim makes every lookup/matmul output
    # feature-sharded, which fights the batch-sharded activation layout
    for d in range(len(shape)):
        if entries[d] is None and shape[d] % size == 0 and shape[d] >= size:
            entries[d] = "fsdp"
            break
    return PartitionSpec(*entries)


def _param_sharding(p, mesh: Mesh, mode: str) -> NamedSharding:
    hint = getattr(p, "_sharding", None)
    if hint is not None and not isinstance(hint, PartitionSpec):
        hint = PartitionSpec(*hint)
    if mode == "fsdp":
        return NamedSharding(mesh, _fsdp_spec(p.shape, mesh, base=hint))
    if hint is not None:
        return NamedSharding(mesh, hint)
    return NamedSharding(mesh, PartitionSpec())


def shard_params(block, mesh: Mesh, mode: str = "replicated"):
    """Place every initialized Parameter of ``block`` onto ``mesh`` with its
    resolved sharding (eager re-placement; the jitted step then runs with
    arrays already resident)."""
    multiproc = jax.process_count() > 1
    for p in block.collect_params().values():
        if p._data is None:
            continue
        sh = _param_sharding(p, mesh, mode)
        arr = p._data._data
        if multiproc and len(arr.devices()) == 1:
            # promote a process-local array to the multi-host mesh: go
            # through the host copy (identical on every process — SPMD
            # programs compute the same init on each rank), the only
            # legal source for a cross-process device_put
            arr = _np_host(arr)
        p._data._data = jax.device_put(arr, sh)


def _np_host(arr):
    import numpy as _np
    return _np.asarray(arr)


class SPMDTrainer:
    """One-fused-step trainer over a mesh (the Trainer fast path).

    Parameters
    ----------
    block : HybridBlock — the model (must be initialized, shapes known).
    loss : callable ``loss(out, *labels) -> NDArray`` (a gluon loss works).
    optimizer : str | Optimizer, with ``optimizer_params`` as for Trainer.
    mesh : jax mesh (default: all devices on ``dp``).
    sharding : 'replicated' | 'fsdp'.
    forward_loss : optional ``fn(block, *batch) -> scalar NDArray`` override
        for models whose loss is not ``loss(block(x), y)`` (e.g. BERT MLM).
    pipeline : optional ``parallel.pipelined.PipelineSpec`` — run the
        step as the pipelined-backward program with in-program bucket
        collectives interleaved between block pullbacks (ROADMAP item 5;
        bit-identical to the GSPMD step on clean streams, asserted in
        tests). ``forward_loss``/``loss`` are ignored when set: the
        spec's head/finalize ARE the loss.
    int8_allreduce : traced in-program int8 gradient all-reduce
        (quantize → psum int32 codes → dequantize, per-bucket scale;
        the PR-11 compression promoted from host-side seam to program
        ops). Default from ``MXTPU_INT8_ALLREDUCE``. Pipeline-only.
    grad_collective : 'psum' (default) or 'ring' — how pipelined bucket
        collectives are emitted; 'ring' uses a collective-permute chunk
        ring for schedulers that cluster all-reduce ops. Env:
        ``MXTPU_GRAD_COLLECTIVE``.
    remat_plan : optional per-pipeline-block remat policy list (entries
        False | True | 'dots'), e.g. from
        ``models._remat.plan_remat_from_profile`` fed by
        ``trace_summary overlap_stats``. Pipeline-only.
    """

    def __init__(self, block, loss=None, optimizer="sgd",
                 optimizer_params=None, mesh: Optional[Mesh] = None,
                 sharding: str = "replicated",
                 forward_loss: Optional[Callable] = None,
                 donate: bool = True, loss_scaler=None,
                 guard: Optional[bool] = None,
                 max_consecutive_nonfinite: Optional[int] = None,
                 pipeline=None, int8_allreduce: Optional[bool] = None,
                 grad_collective: Optional[str] = None,
                 remat_plan: Optional[Sequence] = None):
        if loss is None and forward_loss is None and pipeline is None:
            raise MXNetError("provide loss, forward_loss or pipeline")
        self.block = block
        self.loss = loss
        self.forward_loss = forward_loss
        self.mesh = mesh if mesh is not None else _mesh.default_mesh()
        self.sharding_mode = sharding
        self.donate = donate
        # round-13 resilience (docs/RESILIENCE.md "Training resilience"):
        # the fused step carries an all-finite guard over the gradients
        # as pure traced data (a where-select skip — no retrace, and the
        # skip decision is GLOBAL because the reduction runs inside the
        # SPMD program: every rank sees the same flag by construction);
        # the dynamic loss scale rides as a traced scalar input.
        if guard is None:
            guard = getenv_bool("MXTPU_STEP_GUARD", True)
        self.guard = bool(guard)
        self.loss_scaler = loss_scaler
        if loss_scaler is not None and not self.guard:
            import warnings
            warnings.warn(
                "loss_scaler attached but the in-step guard is off — "
                "overflow detection never fires, so the scale would "
                "only ever grow; scale updates are disabled",
                UserWarning, stacklevel=2)
        self._recorder = StepRecorder(max_consecutive_nonfinite)
        self.step_trace_count = 0    # fused-step compiles (jit-once)
        # round 16 (docs/TRAINING_PERF.md): in-step traced gradient
        # accumulation — ONE once-compiled microbatch program whose
        # accumulation count is pure host data (see step_microbatches)
        self.accum_step_trace_count = 0
        # round 19 (ROADMAP item 5): pipelined-backward step with
        # in-program bucket collectives (parallel/pipelined.py)
        self._pipeline = pipeline
        if int8_allreduce is None:
            int8_allreduce = getenv_bool("MXTPU_INT8_ALLREDUCE", False)
        self._int8_allreduce = bool(int8_allreduce)
        if grad_collective is None:
            import os
            grad_collective = os.environ.get(
                "MXTPU_GRAD_COLLECTIVE", "psum")
        if grad_collective not in ("psum", "ring"):
            raise MXNetError(
                f"grad_collective must be 'psum' or 'ring', got "
                f"{grad_collective!r}")
        if grad_collective == "ring" and self._int8_allreduce:
            raise MXNetError(
                "int8_allreduce composes with grad_collective='psum' "
                "only (the ring carries f32 chunks)")
        self._grad_collective = grad_collective
        self._remat_plan = list(remat_plan) if remat_plan is not None \
            else None
        if pipeline is None and (self._int8_allreduce
                                 or remat_plan is not None):
            raise MXNetError(
                "int8_allreduce / remat_plan require pipeline= (the "
                "GSPMD step has no in-program collective seam)")
        self.pipelined_step_trace_count = 0
        self.pipelined_accum_step_trace_count = 0
        self.pipelined_issue_ledger = None   # set at trace time
        self.pipelined_bucket_order = None
        self._pipe_lowering = False          # suppress counters in .lower
        self._pipe_example_args = None       # ShapeDtypeStruct snapshot
        self._pipe_example_accum_args = None
        self._accum_step_fn = None
        self._accum_bufs = None      # f32 grad accumulators (jax arrays)
        self._accum_ok = None        # carried combined-verdict scalar
        self._accum_loss = None      # carried loss-sum scalar
        self.last_accum_count = 0    # k of the last accumulated round

        params = list(block.collect_params().values())
        not_ready = [p.name for p in params
                     if p._data is None and p._deferred_init is None]
        if not_ready:
            raise MXNetError(
                f"uninitialized parameters: {not_ready}; call "
                f"block.initialize() first")
        self._params = params
        self._train_idx = [i for i, p in enumerate(params)
                           if p.grad_req != "null"]

        if isinstance(optimizer, str):
            pd = {p.name: p for p in params}
            self._optimizer = opt_create(
                optimizer, param_dict=pd,
                param_idx2name={i: params[i].name
                                for i in range(len(params))},
                **(optimizer_params or {}))
        else:
            self._optimizer = optimizer

        self._step_fn = None
        self._opt_state = None  # list aligned with self._train_idx
        self.step_count = 0

    # ------------------------------------------------------------------ #
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- resilience surface (docs/RESILIENCE.md, round 13) --------------- #
    @property
    def health(self) -> dict:
        return self._recorder.health

    @property
    def last_outcome(self):
        return self._recorder.last_outcome

    def health_snapshot(self) -> dict:
        snap = self._recorder.snapshot()
        snap["loss_scale"] = (None if self.loss_scaler is None
                              else float(self.loss_scaler.loss_scale))
        snap["guard"] = self.guard
        snap["step_trace_count"] = self.step_trace_count
        snap["accum_step_trace_count"] = self.accum_step_trace_count
        snap["last_accum_count"] = self.last_accum_count
        if self._pipeline is not None:
            snap["pipelined"] = True
            snap["pipelined_step_trace_count"] = \
                self.pipelined_step_trace_count
            snap["pipelined_accum_step_trace_count"] = \
                self.pipelined_accum_step_trace_count
            snap["pipelined_bucket_order"] = self.pipelined_bucket_order
            snap["grad_collective"] = self._grad_collective
            snap["int8_allreduce"] = self._int8_allreduce
        return snap

    # -- pipelined-step structure surface (parallel/pipelined.py) ------- #
    @staticmethod
    def _abstract_args(args, static=frozenset()):
        """Freeze a call's arguments as ShapeDtypeStructs (static
        positions kept verbatim) so `.lower()` can re-derive the HLO
        later without holding donated buffers alive."""

        def _abs(a):
            return jax.ShapeDtypeStruct(jnp.shape(a),
                                        jnp.result_type(a))
        return tuple(
            a if pos in static else jtu.tree_map(_abs, a)
            for pos, a in enumerate(args))

    def pipelined_hlo(self, accum: bool = False) -> str:
        """Lowered StableHLO text of the pipelined step program (the
        substrate of the structural overlap assertion). Requires one
        prior dispatch (step / step_microbatches) so the example
        signature exists. The re-trace for lowering is excluded from
        the trace counters (`_pipe_lowering`)."""
        if self._pipeline is None:
            raise MXNetError("pipelined_hlo: trainer has no pipeline=")
        fn = self._accum_step_fn if accum else self._step_fn
        args = self._pipe_example_accum_args if accum \
            else self._pipe_example_args
        if fn is None or args is None:
            raise MXNetError(
                "pipelined_hlo: run one step first (the lowering "
                "snapshot is captured at first dispatch)")
        self._pipe_lowering = True
        try:
            return fn.lower(*args).as_text()
        finally:
            self._pipe_lowering = False

    def pipelined_structure(self, accum: bool = False) -> dict:
        """`pipelined.structure_report` over the compiled program: grad
        collectives present per bucket, in plan order, interleaved
        between block backwards (not clustered after them)."""
        from .pipelined import structure_report
        if self.pipelined_issue_ledger is None:
            raise MXNetError(
                "pipelined_structure: run one step first")
        return structure_report(self.pipelined_hlo(accum=accum),
                                self.pipelined_issue_ledger)

    # ------------------------------------------------------------------ #
    def _materialize(self, batch_nds):
        """Finish deferred init with one eager forward, then place params
        (and build optimizer state) with their mesh shardings."""
        if any(p._deferred_init is not None for p in self._params):
            with autograd.pause():
                if self.forward_loss is not None:
                    self.forward_loss(self.block, *batch_nds)
                else:
                    self.block(batch_nds[0])
            self._params = list(self.block.collect_params().values())
            self._train_idx = [i for i, p in enumerate(self._params)
                               if p.grad_req != "null"]
        multiproc = jax.process_count() > 1
        if self._opt_state is None:
            # create optimizer state BEFORE params go onto the global mesh:
            # eager ops (e.g. the multi-precision f32 master cast) are not
            # legal on non-fully-addressable multi-host arrays
            self._opt_state = []
            for i in self._train_idx:
                p = self._params[i]
                st = self._optimizer.create_state_multi_precision(
                    i, p.data())
                sh = _param_sharding(p, self.mesh, self.sharding_mode)

                def _place(s, sh=sh):
                    if not isinstance(s, NDArray):
                        return s
                    arr = s._data
                    if multiproc and len(arr.devices()) == 1:
                        arr = _np_host(arr)
                    return NDArray(jax.device_put(arr, sh))

                st = jtu.tree_map(
                    _place, st, is_leaf=lambda s: isinstance(s, NDArray))
                self._opt_state.append(st)
        shard_params(self.block, self.mesh, self.sharding_mode)

    def _build_step(self, n_batch):
        params = self._params
        train_idx = self._train_idx
        train_set = set(train_idx)
        optimizer = self._optimizer
        block = self.block
        loss = self.loss
        forward_loss = self.forward_loss
        self_mesh = self.mesh
        from ..gluon.block import _hybrid_trace_scope

        def pure_loss(train_vals, frozen_vals, key, *batch):
            """loss + aux (mutated frozen params, e.g. BN running stats)."""
            saved = [p._data for p in params]
            it_t, it_f = iter(train_vals), iter(frozen_vals)
            for i, p in enumerate(params):
                p._data = NDArray(next(it_t) if i in train_set else next(it_f))
            try:
                with _hybrid_trace_scope(), _random.key_provider(key), \
                        autograd._ModeScope(recording=False, training=True), \
                        activation_sharding_scope(self_mesh):
                    batch_nd = [NDArray(b) for b in batch]
                    if forward_loss is not None:
                        L = forward_loss(block, *batch_nd)
                    else:
                        out = block(batch_nd[0])
                        L = loss(out, *batch_nd[1:])
                    if L.ndim > 0:
                        L = L.mean()
                    aux = []
                    for i, p in enumerate(params):
                        if i not in train_set:
                            aux.append(p._data._data)
            finally:
                for p, s in zip(params, saved):
                    p._data = s
            return L._data, tuple(aux)

        guard = self.guard
        trainer = self
        base_rescale = float(optimizer.rescale_grad)

        def step(train_vals, frozen_vals, opt_leaves, opt_tree, t, lr,
                 scale, key, *batch):
            trainer.step_trace_count += 1   # python body = trace time only
            (loss_val, aux), grads = jax.value_and_grad(
                lambda tv, fv, k, *b: (
                    # dynamic loss scaling as a traced scalar: scale the
                    # loss INSIDE the program, divide back through the
                    # (traced) rescale_grad below — growth/decay never
                    # retraces
                    (lambda L, a: (L * scale, a))(*pure_loss(tv, fv, k, *b))
                ), argnums=0, has_aux=True)(
                    train_vals, frozen_vals, key, *batch)
            loss_val = loss_val / scale
            opt_state = jtu.tree_unflatten(opt_tree, opt_leaves)
            # whole-tree fused apply (optimizer/fused.py — shared with the
            # eager Trainer's jitted group path); the step counter and lr
            # arrive as traced scalars so schedules and Adam/LAMB bias
            # correction advance without recompiling
            from ..optimizer.fused import apply_updates
            new_train, new_states = apply_updates(
                optimizer, train_idx, train_vals, grads, opt_state, t, lr,
                rescale_grad=jnp.float32(base_rescale) / scale)
            new_train = tuple(new_train)
            aux = tuple(aux)
            new_leaves = tuple(jtu.tree_leaves(tuple(new_states)))
            if guard:
                # in-step non-finite guard, pure traced data: the
                # all-finite reduction over the (scaled) gradients runs
                # inside the SPMD program — XLA inserts the cross-device
                # reduction itself, so every rank computes the SAME flag
                # — and a skip-step is a where-select of the old params,
                # optimizer state AND mutated frozen params (BN stats)
                from ..optimizer.fused import all_finite
                ok_flag = all_finite(grads)
                apply_p = ok_flag > 0
                new_train = tuple(jnp.where(apply_p, nw, w)
                                  for nw, w in zip(new_train, train_vals))
                aux = tuple(jnp.where(apply_p, na, fv)
                            for na, fv in zip(aux, frozen_vals))
                new_leaves = tuple(jnp.where(apply_p, nl, ol)
                                   for nl, ol in zip(new_leaves,
                                                     opt_leaves))
            else:
                ok_flag = jnp.float32(1.0)
            return new_train, aux, new_leaves, loss_val, ok_flag

        repl, batch_sh, train_sh, frozen_sh, state_sh = \
            self._step_shardings()

        donate = (0, 2) if self.donate else ()
        return jax.jit(
            step,
            static_argnums=(3,),
            in_shardings=(train_sh, frozen_sh, tuple(state_sh), repl, repl,
                          repl, repl) + (batch_sh,) * n_batch,
            # pin outputs to the param/state shardings: otherwise the
            # partitioner may emit its preferred layout and step N+1's
            # donated inputs no longer match in_shardings
            out_shardings=(train_sh, frozen_sh, tuple(state_sh), repl,
                           repl),
            donate_argnums=donate)

    def _step_shardings(self):
        mesh = self.mesh
        params = self._params
        train_set = set(self._train_idx)
        repl = NamedSharding(mesh, PartitionSpec())
        batch_sh = NamedSharding(mesh, PartitionSpec(("fsdp", "dp")))
        train_sh = tuple(
            _param_sharding(params[i], mesh, self.sharding_mode)
            for i in self._train_idx)
        frozen_sh = tuple(
            _param_sharding(params[i], mesh, self.sharding_mode)
            for i in range(len(params)) if i not in train_set)
        # optimizer-state leaves share their parameter's sharding
        state_sh = []
        for slot, i in enumerate(self._train_idx):
            n_leaves = len(jtu.tree_leaves(
                jtu.tree_map(lambda s: 0, self._opt_state[slot],
                             is_leaf=lambda s: isinstance(s, NDArray))))
            state_sh.extend(
                [_param_sharding(params[i], mesh, self.sharding_mode)]
                * n_leaves)
        return repl, batch_sh, train_sh, frozen_sh, state_sh

    # ------------------------------------------------------------------ #
    # in-step traced gradient accumulation (round 16,
    # docs/TRAINING_PERF.md). ONE once-compiled program processes one
    # microbatch per call and carries (f32 grad accumulators, combined
    # all-finite verdict, loss sum) as donated state; ``is_last`` and
    # ``inv_k`` ride as traced scalars, so the accumulation count k is
    # PURE HOST DATA — changing k between rounds never retraces
    # (``accum_step_trace_count`` asserted; the scan-over-k alternative
    # recompiles per count because the reshaped batch changes shape).
    # The apply is a where-select on ``is_last AND all-micros-finite``:
    # a NaN in microbatch 2 of 8 poisons the carried verdict and the
    # whole apply skips, params/optimizer state bit-identical — ONE
    # combined verdict, ONE StepOutcome, ONE loss-scaler update per
    # accumulated step (the PR-8 guard/scaler contract, composed).
    # ------------------------------------------------------------------ #
    def _build_accum_step(self, n_batch):
        params = self._params
        train_idx = self._train_idx
        train_set = set(train_idx)
        optimizer = self._optimizer
        block = self.block
        loss = self.loss
        forward_loss = self.forward_loss
        self_mesh = self.mesh
        from ..gluon.block import _hybrid_trace_scope

        def pure_loss(train_vals, frozen_vals, key, *batch):
            saved = [p._data for p in params]
            it_t, it_f = iter(train_vals), iter(frozen_vals)
            for i, p in enumerate(params):
                p._data = NDArray(next(it_t) if i in train_set
                                  else next(it_f))
            try:
                with _hybrid_trace_scope(), _random.key_provider(key), \
                        autograd._ModeScope(recording=False,
                                            training=True), \
                        activation_sharding_scope(self_mesh):
                    batch_nd = [NDArray(b) for b in batch]
                    if forward_loss is not None:
                        L = forward_loss(block, *batch_nd)
                    else:
                        out = block(batch_nd[0])
                        L = loss(out, *batch_nd[1:])
                    if L.ndim > 0:
                        L = L.mean()
                    aux = []
                    for i, p in enumerate(params):
                        if i not in train_set:
                            aux.append(p._data._data)
            finally:
                for p, s in zip(params, saved):
                    p._data = s
            return L._data, tuple(aux)

        guard = self.guard
        trainer = self
        base_rescale = float(optimizer.rescale_grad)

        def astep(train_vals, frozen_vals, opt_leaves, opt_tree,
                  acc_vals, acc_ok, acc_loss, t, lr, scale, inv_k,
                  is_last, key, *batch):
            trainer.accum_step_trace_count += 1   # trace time only
            (loss_val, aux), grads = jax.value_and_grad(
                lambda tv, fv, k, *b: (
                    (lambda L, a: (L * scale, a))(*pure_loss(tv, fv, k,
                                                             *b))
                ), argnums=0, has_aux=True)(
                    train_vals, frozen_vals, key, *batch)
            loss_val = loss_val / scale
            # fold this microbatch into the f32 accumulators; non-finite
            # values propagate through the sum AND the explicit verdict
            # product below, so the round's apply decision is combined
            new_acc = tuple(a + g.astype(jnp.float32)
                            for a, g in zip(acc_vals, grads))
            from ..optimizer.fused import all_finite, apply_updates
            if guard:
                ok_round = acc_ok * all_finite(grads)
            else:
                ok_round = jnp.float32(1.0)
            loss_round = acc_loss + loss_val
            # the apply (mean of the accumulated f32 gradients), always
            # traced, selected only on the last microbatch of a clean
            # round — the where-select skip idiom of the PR-8 guard,
            # extended with the is_last gate
            opt_state = jtu.tree_unflatten(opt_tree, opt_leaves)
            apply_grads = tuple(a * inv_k for a in new_acc)
            new_train, new_states = apply_updates(
                optimizer, train_idx, train_vals, apply_grads, opt_state,
                t, lr, rescale_grad=jnp.float32(base_rescale) / scale)
            new_leaves = tuple(jtu.tree_leaves(tuple(new_states)))
            last_p = is_last > 0
            apply_p = jnp.logical_and(last_p, ok_round > 0)
            new_train = tuple(jnp.where(apply_p, nw, w)
                              for nw, w in zip(new_train, train_vals))
            new_leaves = tuple(jnp.where(apply_p, nl, ol)
                               for nl, ol in zip(new_leaves, opt_leaves))
            # accumulators reset at round end regardless of verdict (a
            # vetoed round's batch is discarded, PR-8 skip semantics)
            acc_out = tuple(jnp.where(last_p, jnp.zeros_like(na), na)
                            for na in new_acc)
            acc_ok_out = jnp.where(last_p, jnp.float32(1.0), ok_round)
            acc_loss_out = jnp.where(last_p, jnp.float32(0.0),
                                     loss_round)
            return (new_train, tuple(aux), new_leaves, acc_out,
                    acc_ok_out, acc_loss_out, loss_round * inv_k,
                    ok_round)

        repl, batch_sh, train_sh, frozen_sh, state_sh = \
            self._step_shardings()
        acc_sh = train_sh                 # accumulators shard like params
        donate = (0, 2, 4) if self.donate else ()
        return jax.jit(
            astep,
            static_argnums=(3,),
            in_shardings=(train_sh, frozen_sh, tuple(state_sh), acc_sh,
                          repl, repl, repl, repl, repl, repl, repl,
                          repl) + (batch_sh,) * n_batch,
            out_shardings=(train_sh, frozen_sh, tuple(state_sh), acc_sh,
                           repl, repl, repl, repl),
            donate_argnums=donate)

    def step_microbatches(self, microbatches):
        """Run ONE optimizer step over ``microbatches`` (a sequence of
        batch tuples of identical shapes), accumulating gradients in
        f32 inside the once-compiled microbatch program and applying
        the mean once at the end. The accumulation count is pure host
        data — rounds of 1, 4 and 8 microbatches all run the same
        compiled program (``accum_step_trace_count`` stays 1; changing
        the MICROBATCH SHAPE retraces, changing the count never does).
        The PR-8 guard/scaler contract composes as one round-level
        verdict: a non-finite gradient in ANY microbatch skips the
        whole apply (params, optimizer state and BN aux bit-identical
        to the round start), records ONE ``SKIPPED_NONFINITE`` and
        halves the loss scale ONCE. Returns the round's mean loss."""
        batches = [b if isinstance(b, (tuple, list)) else (b,)
                   for b in microbatches]
        if not batches:
            raise MXNetError("step_microbatches needs >= 1 microbatch")
        k = len(batches)
        dp = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        rounds = []
        for batch in batches:
            nds = [b if isinstance(b, NDArray)
                   else NDArray(jnp.asarray(b)) for b in batch]
            for b in nds:
                if b.ndim and b.shape[0] % dp != 0:
                    raise MXNetError(
                        f"microbatch dim {b.shape[0]} not divisible by "
                        f"the mesh's dp×fsdp size {dp}")
            rounds.append(nds)
        if self._opt_state is None:
            self._materialize(rounds[0])
        if self._accum_step_fn is None:
            if self._pipeline is not None:
                from .pipelined import build_pipelined_accum_step
                self._accum_step_fn = build_pipelined_accum_step(
                    self, len(rounds[0]))
            else:
                self._accum_step_fn = self._build_accum_step(
                    len(rounds[0]))
        if self._accum_bufs is None:
            # f32 accumulators placed with their parameter's sharding
            repl, _, train_sh, _, _ = self._step_shardings()
            self._accum_bufs = [
                jax.device_put(
                    jnp.zeros(self._params[i].shape, jnp.float32), sh)
                for i, sh in zip(self._train_idx, train_sh)]
            self._accum_ok = jnp.float32(1.0)
            self._accum_loss = jnp.float32(0.0)

        import numpy as _host_np
        train_set = set(self._train_idx)
        self._optimizer.num_update = self.step_count
        t = _host_np.float32(self.step_count + 1)
        lr = _host_np.float32(float(self._optimizer.learning_rate))
        scale = _host_np.float32(
            1.0 if self.loss_scaler is None
            else self.loss_scaler.loss_scale)
        inv_k = _host_np.float32(1.0 / k)
        # round-start frozen-param snapshot (array refs, not copies):
        # BN running stats advance per microbatch, and a vetoed round
        # must roll NOTHING forward — restored below on veto
        frozen_saved = [p._data._data
                        for i, p in enumerate(self._params)
                        if i not in train_set]

        self._recorder.open_step()
        loss_report = ok_report = None
        try:
            for m, batch_nds in enumerate(rounds):
                is_last = _host_np.float32(1.0 if m == k - 1 else 0.0)
                key = _random.new_key()
                train_vals = tuple(self._params[i]._data._data
                                   for i in self._train_idx)
                frozen_vals = tuple(
                    p._data._data for i, p in enumerate(self._params)
                    if i not in train_set)
                opt_leaves, opt_tree = jtu.tree_flatten(
                    jtu.tree_map(
                        lambda s: s._data if isinstance(s, NDArray)
                        else s,
                        tuple(self._opt_state),
                        is_leaf=lambda s: isinstance(s, NDArray)))
                batch_vals = self._global_batch_vals(
                    [b._data for b in batch_nds])
                if jax.process_count() > 1:
                    key = _host_np.asarray(key)
                if self._pipeline is not None and \
                        self._pipe_example_accum_args is None:
                    self._pipe_example_accum_args = self._abstract_args(
                        (train_vals, frozen_vals, tuple(opt_leaves),
                         opt_tree, tuple(self._accum_bufs),
                         self._accum_ok, self._accum_loss, t, lr,
                         scale, inv_k, is_last, key)
                        + tuple(batch_vals), static={3})
                (new_train, aux, new_leaves, acc_out, acc_ok_out,
                 acc_loss_out, loss_report, ok_report) = \
                    self._accum_step_fn(
                        train_vals, frozen_vals, tuple(opt_leaves),
                        opt_tree, tuple(self._accum_bufs),
                        self._accum_ok, self._accum_loss, t, lr, scale,
                        inv_k, is_last, key, *batch_vals)
                it_t, it_a = iter(new_train), iter(aux)
                for i, p in enumerate(self._params):
                    p._data._data = next(it_t) if i in train_set \
                        else next(it_a)
                self._opt_state = [
                    jtu.tree_map(NDArray, st)
                    for st in jtu.tree_unflatten(opt_tree,
                                                 list(new_leaves))]
                self._accum_bufs = list(acc_out)
                self._accum_ok = acc_ok_out
                self._accum_loss = acc_loss_out
        except BaseException:
            # dispatch died mid-round: close the step and drop the
            # half-accumulated state (re-zeroed on the next round)
            self._recorder.abort_step()
            self._accum_bufs = None
            raise

        self.last_accum_count = k
        # the ONE designed readback per accumulated round: the combined
        # verdict steers host counters, the scaler and the outcome —
        # read after every microbatch is dispatched
        applied = (not self.guard) or \
            bool(_host_np.asarray(ok_report) > 0)
        if applied:
            self.step_count += 1
            self._recorder.record(StepOutcome.APPLIED)
            if self.loss_scaler is not None and self.guard:
                self.loss_scaler.update_scale(overflow=False)
        else:
            # roll the per-microbatch BN/aux mutations back to the
            # round start — a vetoed round rolls NOTHING forward
            it_f = iter(frozen_saved)
            for i, p in enumerate(self._params):
                if i not in train_set:
                    p._data._data = next(it_f)
            if self.loss_scaler is not None:
                self.loss_scaler.update_scale(overflow=True)
            detail = (f"non-finite gradient in accumulated SPMD round "
                      f"(k={k}) at step_count={self.step_count}")
            outcome = self._recorder.record(
                StepOutcome.SKIPPED_NONFINITE, detail)
            if outcome is StepOutcome.HALTED_POISONED:
                raise self._recorder.halt_error(
                    detail,
                    loss_scale=None if self.loss_scaler is None
                    else self.loss_scaler.loss_scale)
        return NDArray(loss_report)

    def _global_batch_vals(self, batch_vals):
        """Multi-host batch placement (every process holds the SAME full
        batch; build global dp-sharded arrays from the host copies) —
        identity in single-process runs."""
        if jax.process_count() <= 1:
            return batch_vals
        import numpy as _host_np
        batch_sh = NamedSharding(self.mesh,
                                 PartitionSpec(("fsdp", "dp")))

        def _globalize(b):
            if len(b.devices()) > 1:
                return b
            host = _host_np.asarray(b)
            if host.ndim == 0:
                return host
            return jax.make_array_from_callback(
                host.shape, batch_sh, lambda idx: host[idx])

        return [_globalize(b) for b in batch_vals]

    # ------------------------------------------------------------------ #
    def step(self, *batch):
        """Run one fused train step; returns the (device-resident) loss."""
        batch_nds = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
                     for b in batch]
        dp = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        for b in batch_nds:
            if b.ndim and b.shape[0] % dp != 0:
                raise MXNetError(
                    f"batch dim {b.shape[0]} not divisible by the mesh's "
                    f"dp×fsdp size {dp}; pad the batch or shrink the mesh")
        if self._opt_state is None:
            self._materialize(batch_nds)
        if self._step_fn is None:
            if self._pipeline is not None:
                from .pipelined import build_pipelined_step
                self._step_fn = build_pipelined_step(
                    self, len(batch_nds))
            else:
                self._step_fn = self._build_step(len(batch_nds))

        train_vals = tuple(self._params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(p._data._data for i, p in enumerate(self._params)
                            if i not in set(self._train_idx))
        state_nd = tuple(self._opt_state)
        opt_leaves, opt_tree = jtu.tree_flatten(
            jtu.tree_map(lambda s: s._data if isinstance(s, NDArray) else s,
                         state_nd,
                         is_leaf=lambda s: isinstance(s, NDArray)))
        import numpy as _host_np
        key = _random.new_key()
        self._optimizer.num_update = self.step_count  # drive lr schedules
        t = _host_np.float32(self.step_count + 1)
        lr = _host_np.float32(float(self._optimizer.learning_rate))
        scale = _host_np.float32(
            1.0 if self.loss_scaler is None
            else self.loss_scaler.loss_scale)
        batch_vals = self._global_batch_vals([b._data for b in batch_nds])
        if jax.process_count() > 1:
            key = _host_np.asarray(key)
        if self._pipeline is not None and self._pipe_example_args is None:
            # abstract snapshot for on-demand .lower() (structure checks)
            self._pipe_example_args = self._abstract_args(
                (train_vals, frozen_vals, tuple(opt_leaves), opt_tree,
                 t, lr, scale, key) + tuple(batch_vals), static={3})

        self._recorder.open_step()
        try:
            new_train, aux, new_state_leaves, loss_val, ok_flag = \
                self._step_fn(
                    train_vals, frozen_vals, tuple(opt_leaves), opt_tree,
                    t, lr, scale, key, *batch_vals)
        except BaseException:
            # dispatch died before any outcome existed — close the step
            # so the next one is not falsely accused of a missing record
            self._recorder.abort_step()
            raise

        train_set = set(self._train_idx)
        it_t = iter(new_train)
        it_a = iter(aux)
        for i, p in enumerate(self._params):
            p._data._data = next(it_t) if i in train_set else next(it_a)
        new_states = jtu.tree_unflatten(opt_tree, list(new_state_leaves))
        self._opt_state = [
            jtu.tree_map(NDArray, st) for st in new_states]
        # the guard verdict is read AFTER the outputs are bound (the
        # update was already selected on device); it only steers host
        # counters, the scaler and the outcome record
        applied = (not self.guard) or bool(_host_np.asarray(ok_flag) > 0)
        if applied:
            self.step_count += 1
            self._recorder.record(StepOutcome.APPLIED)
            if self.loss_scaler is not None and self.guard:
                # without the guard overflow can never be observed, so
                # growing the scale would be a one-way ratchet to inf
                self.loss_scaler.update_scale(overflow=False)
        else:
            if self.loss_scaler is not None:
                self.loss_scaler.update_scale(overflow=True)
            detail = (f"non-finite gradient in fused SPMD step at "
                      f"step_count={self.step_count} "
                      f"(loss={float(_host_np.asarray(loss_val)):g})")
            outcome = self._recorder.record(
                StepOutcome.SKIPPED_NONFINITE, detail)
            if outcome is StepOutcome.HALTED_POISONED:
                raise self._recorder.halt_error(
                    detail,
                    loss_scale=None if self.loss_scaler is None
                    else self.loss_scaler.loss_scale)
        return NDArray(loss_val)

    # ------------------------------------------------------------------ #
    # elastic checkpointing (checkpoint/ subsystem): each process
    # gathers only its addressable shards, so fsdp-sharded params and
    # optimizer state checkpoint without ever materializing the full
    # tree on one host. Restore hands host arrays to the jitted step,
    # which re-places them via its in_shardings — resume on the SAME
    # mesh is bit-exact (asserted in tests); a different mesh shape
    # loads and trains correctly but reduction order may differ in the
    # last ulp.
    # ------------------------------------------------------------------ #
    def save_checkpoint(self, manager, step=None, iterator=None,
                        block=False):
        """Async full-capsule snapshot (params, optimizer state, step
        count, scheduler num_update, RNG, iterator position)."""
        from .. import checkpoint as _ckpt
        tree, meta = _ckpt.spmd_capsule(self, iterator=iterator)
        if step is None:
            step = meta["step"]
        else:
            # caller's loop position wins (see Trainer.save_checkpoint:
            # step_count does not advance on guard-skipped steps, and a
            # resume must not re-run already-applied batches)
            meta["step"] = int(step)
        manager.save(int(step), tree, meta=meta, block=block)
        return int(step)

    def restore_checkpoint(self, manager, step=None, iterator=None):
        """Bit-exact resume from ``manager`` (default: latest committed
        step). The block must be initialized with known shapes; the
        jitted step is rebuilt lazily and re-places restored host
        arrays via its in_shardings. Returns the restored step."""
        from .. import checkpoint as _ckpt
        arrays, meta = manager.restore(step)
        _ckpt.restore_spmd(self, arrays, meta, iterator=iterator)
        return int(meta.get("step", 0))

    def install_preemption(self, manager, iterator=None, exit_after=True):
        """Arm SIGTERM: drain any in-flight snapshot, write one final
        synchronous capsule, then let the process die."""
        from .. import checkpoint as _ckpt

        def _state():
            tree, meta = _ckpt.spmd_capsule(self, iterator=iterator)
            return meta["step"], tree, meta

        return manager.install_preemption_hook(_state,
                                               exit_after=exit_after)
