"""RecordIO: the reference's binary record format, byte-compatible.

Re-design of `python/mxnet/recordio.py` over dmlc recordio
(`3rdparty/dmlc-core/src/recordio.cc`; file-level citations — SURVEY.md
caveat §3.5). Files written by the reference's ``im2rec`` load here and
vice versa:

    record  := magic(u32) | cflag_len(u32) | payload | pad to 4B
    magic   =  0xced7230a
    cflag   =  top 3 bits (0=whole, 1=first, 2=middle, 3=last chunk)
    length  =  low 29 bits

When the native reader (src/, libmxtpu_io.so via ctypes) is available it
does chunked file IO + record splitting off the Python thread; this module
is the always-available pure-Python path and the writer.
"""

from __future__ import annotations

import collections
import os
import struct
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["MXRecordIO", "IndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record reader/writer (parity: mx.recordio.MXRecordIO)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self._fp = None
        self.open()

    def open(self):
        if self.flag == "w":
            self._fp = open(self.uri, "wb")
        elif self.flag == "r":
            self._fp = open(self.uri, "rb")
        else:
            raise MXNetError(f"invalid flag {self.flag!r}")
        self.writable = self.flag == "w"

    def close(self):
        if self._fp is not None:
            self._fp.close()
            self._fp = None

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Support pickling across DataLoader worker forks (the reference
        re-opens the file in the child — fork-handler contract)."""
        d = dict(self.__dict__)
        d["_fp"] = None
        d["_pos"] = self.tell() if (not self.writable
                                    and self._fp is not None) else 0
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        if not self.writable:
            self._fp.seek(pos)

    def write(self, buf: bytes):
        if not self.writable:
            raise MXNetError("not opened for writing")
        header = struct.pack("<II", _MAGIC, len(buf) & _LEN_MASK)
        self._fp.write(header)
        self._fp.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self._fp.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        if self.writable:
            raise MXNetError("not opened for reading")
        header = self._fp.read(8)
        if len(header) < 8:
            return None
        magic, clen = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError(f"invalid record magic {magic:#x} in {self.uri}")
        length = clen & _LEN_MASK
        payload = self._fp.read(length)
        pad = (-length) % 4
        if pad:
            self._fp.read(pad)
        return payload

    def tell(self) -> int:
        return self._fp.tell()

    def seek(self, pos: int):
        if self.writable:
            raise MXNetError("seek is read-mode only")
        self._fp.seek(pos)


class IndexedRecordIO(MXRecordIO):
    """Random-access records through a ``.idx`` sidecar
    (parity: mx.recordio.MXIndexedRecordIO)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type=int):
        self.idx_path = idx_path
        self.idx: Dict = {}
        self.keys: List = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.writable and self._fp is not None and self.idx:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def read_idx(self, idx) -> bytes:
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self._fp.tell()
        self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload (parity: mx.recordio.pack). ``flag > 0``
    means the label is a float array of that length prepended to payload."""
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)):
        label_arr = np.asarray(label, np.float32)
        header = header._replace(flag=label_arr.size, label=0.0)
        s = label_arr.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(header.label),
                       header.id, header.id2) + s


def unpack(s: bytes):
    """(header, payload) (parity: mx.recordio.unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(payload, np.float32, header.flag)
        payload = payload[header.flag * 4:]
        header = header._replace(label=label)
    return header, payload


def pack_img(header: IRHeader, img: np.ndarray, quality=95,
             img_fmt=".jpg") -> bytes:
    """Encode an image into a record (requires cv2 or PIL; raw .npy
    fallback keeps the pipeline hermetic without them)."""
    payload = _encode_img(img, quality, img_fmt)
    return pack(header, payload)


def unpack_img(s: bytes, iscolor=1):
    header, payload = unpack(s)
    return header, _decode_img(payload, iscolor)


def _encode_img(img, quality, img_fmt):
    try:
        import cv2
        ok, buf = cv2.imencode(img_fmt, img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        if not ok:
            raise MXNetError("cv2.imencode failed")
        return buf.tobytes()
    except ImportError:
        pass
    import io as _io
    try:
        from PIL import Image
        bio = _io.BytesIO()
        Image.fromarray(img).save(bio, format="JPEG", quality=quality)
        return bio.getvalue()
    except ImportError:
        bio = _io.BytesIO()
        np.save(bio, np.asarray(img))
        return b"NPY0" + bio.getvalue()


def _decode_img(payload: bytes, iscolor):
    from ..image import decode_to_numpy

    return decode_to_numpy(payload, flag=iscolor, to_rgb=bool(iscolor))


# the reference's canonical class name (IndexedRecordIO kept as the
# shorter local spelling)
MXIndexedRecordIO = IndexedRecordIO
