"""``gluon.contrib`` (parity: python/mxnet/gluon/contrib/)."""

from . import nn
from . import rnn
from . import estimator
from .estimator import Estimator

__all__ = ["nn", "rnn", "estimator", "Estimator"]
