"""ctypes binding for the native IO engine (src/io/recordio_native.cc).

Auto-builds the shared library on first use when a toolchain is present
(the image bakes g++); every caller must handle ``lib() is None`` and fall
back to the pure-Python path — native is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "lib",
                         "libmxtpu_io.so")


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None if
    unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_LIB_PATH):
            if os.environ.get("MXTPU_NO_NATIVE"):
                return None
            try:
                subprocess.run(["make", "-C", _SRC_DIR, "io"],
                               check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            l = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        l.mxtpu_rio_open.restype = ctypes.c_void_p
        l.mxtpu_rio_open.argtypes = [ctypes.c_char_p]
        l.mxtpu_rio_close.argtypes = [ctypes.c_void_p]
        l.mxtpu_rio_scan.restype = ctypes.c_int64
        l.mxtpu_rio_scan.argtypes = [ctypes.c_void_p]
        l.mxtpu_rio_count.restype = ctypes.c_int64
        l.mxtpu_rio_count.argtypes = [ctypes.c_void_p]
        l.mxtpu_rio_index.restype = ctypes.c_int64
        l.mxtpu_rio_index.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p, ctypes.c_int64]
        l.mxtpu_rio_read_at.restype = ctypes.c_int64
        l.mxtpu_rio_read_at.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_void_p, ctypes.c_int64]
        l.mxtpu_rio_read_batch.restype = ctypes.c_int64
        l.mxtpu_rio_read_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        _LIB = l
        return _LIB


class NativeRecordReader:
    """Random-access RecordIO reader backed by the native engine."""

    def __init__(self, path: str, n_threads: int = 4):
        l = lib()
        if l is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = l
        self._path = path
        self._n_threads = n_threads
        self._h = l.mxtpu_rio_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open {path}")
        n = l.mxtpu_rio_scan(self._h)
        if n < 0:
            raise OSError(f"corrupt recordio file {path} (code {n})")
        self.offsets = np.empty(n, np.int64)
        self.lengths = np.empty(n, np.int64)
        l.mxtpu_rio_index(self._h, self.offsets.ctypes.data,
                          self.lengths.ctypes.data, n)

    def __len__(self):
        return len(self.offsets)

    def read(self, i: int) -> bytes:
        length = int(self.lengths[i])
        buf = ctypes.create_string_buffer(length)
        got = self._lib.mxtpu_rio_read_at(self._h, int(self.offsets[i]),
                                          buf, length)
        if got != length:
            raise OSError(f"short read on record {i} (code {got})")
        return buf.raw

    def read_batch(self, indices) -> list:
        idx = np.asarray(indices, np.int64)
        offs = self.offsets[idx]
        total = int(self.lengths[idx].sum())
        out = ctypes.create_string_buffer(total)
        lens = np.empty(len(idx), np.int64)
        got = self._lib.mxtpu_rio_read_batch(
            self._h, np.ascontiguousarray(offs).ctypes.data, len(idx),
            out, total, lens.ctypes.data, self._n_threads)
        if got < 0:
            raise OSError(f"batch read failed (code {got})")
        res = []
        pos = 0
        raw = out.raw
        for n in lens:
            res.append(raw[pos:pos + int(n)])
            pos += int(n)
        return res

    def close(self):
        if getattr(self, "_h", None):
            self._lib.mxtpu_rio_close(self._h)
            self._h = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        return {"path": self._path, "n_threads": self._n_threads}

    def __setstate__(self, d):
        self.__init__(d["path"], d["n_threads"])
