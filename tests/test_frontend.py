"""HTTP/SSE front-end tests (serve/frontend.py).

The load-bearing claims (round 18, docs/SERVING.md "Client
protocol"):

  1. the Outcome -> HTTP status map is TOTAL (every Outcome member
     mapped — adding an outcome without deciding its status fails
     here) and DISTINCT per failure class, golden-tested;
  2. an SSE stream delivers tokens INCREMENTALLY (client-side receive
     stamps spread across the generation, not one burst) and its
     final event carries the terminal outcome;
  3. a mid-stream client disconnect becomes ``backend.cancel``: the
     request terminates CANCELLED, pages are reclaimed (audit), and
     the response tally records 499;
  4. live status mapping: shed -> 429 with a real Retry-After header,
     deadline -> 504, unservable -> 422, malformed -> 400;
  5. tier/deadline/seed and the whole sampling menu ride the JSON
     schema: equal-seed requests reproduce, stop sequences truncate
     (and the holdback means a client never RECEIVES a token the
     match retracts), grammar-constrained output is in-language;
  6. ``/metrics`` serves the backend snapshot plus frontend counters,
     ``/healthz`` answers, and the client edge lands on the flight
     recorder (frontend-lane SUBMIT/ADMIT/TERMINAL with http_status,
     exactly one TERMINAL per request).
"""

import socket
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Outcome,
                                       Request, ServeFrontend,
                                       OUTCOME_HTTP_STATUS,
                                       stream_completion)
from incubator_mxnet_tpu.serve.events import EventType
from incubator_mxnet_tpu.serve.frontend import http_request


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=64, max_length=64)
    m.initialize()
    return m


def _eng(model, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 64)
    kw.setdefault("recorder", False)
    return InferenceEngine(model, **kw)


def _wait_finished(fe, n=1, timeout=20.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if len(fe.finished) >= n:
            return list(fe.finished)
        time.sleep(0.02)
    raise AssertionError(f"only {len(fe.finished)}/{n} requests "
                         f"finished within {timeout}s")


# --------------------------------------------------------------------- #
# the status map golden
# --------------------------------------------------------------------- #

def test_outcome_status_map_is_total_and_distinct():
    # TOTAL: a new Outcome without a decided status must fail HERE
    assert set(OUTCOME_HTTP_STATUS) == set(Outcome)
    # success outcomes share 200; every failure status is DISTINCT
    ok = {o for o in Outcome if o.ok}
    assert all(OUTCOME_HTTP_STATUS[o] == 200 for o in ok)
    fail_statuses = [OUTCOME_HTTP_STATUS[o] for o in Outcome
                     if not o.ok]
    assert len(fail_statuses) == len(set(fail_statuses))
    assert all(s >= 400 for s in fail_statuses)
    # the documented pins (docs/SERVING.md "Client protocol")
    assert OUTCOME_HTTP_STATUS[Outcome.SHED] == 429
    assert OUTCOME_HTTP_STATUS[Outcome.DEADLINE_EXPIRED] == 504
    assert OUTCOME_HTTP_STATUS[Outcome.FAILED_REPLICA] == 502
    assert OUTCOME_HTTP_STATUS[Outcome.PREEMPTED] == 503
    assert OUTCOME_HTTP_STATUS[Outcome.FAILED_UNSERVABLE] == 422
    assert OUTCOME_HTTP_STATUS[Outcome.CANCELLED] == 499
    assert OUTCOME_HTTP_STATUS[Outcome.FAILED_NONFINITE] == 500


# --------------------------------------------------------------------- #
# end-to-end over localhost
# --------------------------------------------------------------------- #

def test_blocking_completion_matches_direct_engine(model):
    prompt = [5, 6, 7, 8]
    direct = _eng(model)
    ref = Request(np.array(prompt, np.int32), max_new_tokens=8)
    direct.run([ref])
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        status, headers, body = http_request(
            "127.0.0.1", fe.bound_port, "POST", "/v1/completions",
            {"prompt": prompt, "max_new_tokens": 8, "stream": False})
    assert status == 200
    assert body["outcome"] == "MAX_TOKENS"
    assert body["tokens"] == list(ref.token_ids)
    assert body["n_tokens"] == 8
    eng.audit_pages()


def test_sse_streams_tokens_incrementally(model):
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        out = stream_completion("127.0.0.1", fe.bound_port,
                                {"prompt": [3, 4, 5],
                                 "max_new_tokens": 24})
    assert out["status"] == 200
    assert "x-request-id" in out["headers"]
    assert out["final"]["outcome"] == "MAX_TOKENS"
    assert len(out["tokens"]) == 24
    # incremental delivery: receive stamps must spread over several
    # distinct arrivals, not one terminal burst (>= 3 tolerates a
    # loaded box batching some reads; a burst delivery would be 1)
    distinct = len({round(s, 4) for s in out["stamps"]})
    assert distinct >= 3, f"tokens arrived in {distinct} bursts"
    assert eng.decode_trace_count == 1
    eng.audit_pages()


def test_disconnect_mid_stream_cancels_and_reclaims(model):
    eng = _eng(model)
    free0 = eng._alloc.free_count
    with ServeFrontend(eng) as fe:
        out = stream_completion("127.0.0.1", fe.bound_port,
                                {"prompt": [8, 9, 10],
                                 "max_new_tokens": 48},
                                abort_after_tokens=2)
        assert out["aborted"]
        finished = _wait_finished(fe)
        assert finished[0].outcome is Outcome.CANCELLED
        snap = fe.stats_snapshot()
        assert snap["disconnects"] == 1
        assert snap["http_responses"].get("499") == 1
    eng.audit_pages()
    assert eng._alloc.free_count == free0       # pages reclaimed


def test_disconnect_detected_when_queue_never_runs_dry(model):
    """Review regression: a backend producing tokens faster than the
    socket drains keeps the per-stream queue non-empty on every wait —
    the connection watch must still win (checked FIRST), or a
    disconnect is masked until the stream ends and the cancel never
    reclaims capacity. A speculative fleet is the fast-burst case."""
    from incubator_mxnet_tpu.serve import build_fleet
    fleet = build_fleet(model, 2,
                        engine_kw=dict(num_slots=2, page_size=8,
                                       max_len=64, spec_k=3,
                                       recorder=False),
                        recorder=False)
    with ServeFrontend(fleet) as fe:
        out = stream_completion("127.0.0.1", fe.bound_port,
                                {"prompt": [8, 9],
                                 "max_new_tokens": 60},
                                abort_after_tokens=2)
        assert out["aborted"]
        finished = _wait_finished(fe)
        # with the masked watch this ends MAX_TOKENS, not CANCELLED
        assert finished[0].outcome is Outcome.CANCELLED
        assert len(finished[0].token_ids) < 60
    for rep in fleet.replicas:
        rep.engine.audit_pages()


def test_live_status_mapping_shed_deadline_unservable(model):
    # SHED: a zero-depth queue refuses immediately -> 429 + Retry-After
    eng = _eng(model, max_queue=0)
    with ServeFrontend(eng) as fe:
        status, headers, body = http_request(
            "127.0.0.1", fe.bound_port, "POST", "/v1/completions",
            {"prompt": [1, 2], "max_new_tokens": 4, "stream": False})
        assert status == 429
        assert body["outcome"] == "SHED"
        assert "retry-after" in headers
        assert int(headers["retry-after"]) >= 1
        assert body["retry_after_s"] > 0
    # FAILED_UNSERVABLE: too big for the pool -> 422
    eng2 = _eng(model)
    with ServeFrontend(eng2) as fe:
        status, _, body = http_request(
            "127.0.0.1", fe.bound_port, "POST", "/v1/completions",
            {"prompt": [1] * 40, "max_new_tokens": 60,
             "stream": False})
        assert status == 422
        assert body["outcome"] == "FAILED_UNSERVABLE"
    # DEADLINE_EXPIRED: queued behind a busy slot past its deadline
    # -> 504 (+ Retry-After: deadline-class outcomes are retryable)
    eng3 = _eng(model, num_slots=1)
    with ServeFrontend(eng3) as fe:
        hold = {}

        def long_stream():
            hold["out"] = stream_completion(
                "127.0.0.1", fe.bound_port,
                {"prompt": [2, 3, 4], "max_new_tokens": 48})

        t = threading.Thread(target=long_stream, daemon=True)
        t.start()
        # wait until the long request owns the slot
        t0 = time.perf_counter()
        while eng3.active_count == 0 and time.perf_counter() - t0 < 10:
            time.sleep(0.01)
        status, headers, body = http_request(
            "127.0.0.1", fe.bound_port, "POST", "/v1/completions",
            {"prompt": [5, 6], "max_new_tokens": 4, "stream": False,
             "deadline_s": 0.01})
        assert status == 504
        assert body["outcome"] == "DEADLINE_EXPIRED"
        assert "retry-after" in headers
        t.join(timeout=30)
        assert hold["out"]["final"]["outcome"] == "MAX_TOKENS"
        # exactly-once response accounting: the blocking 504 is
        # counted at stream retirement only, never again by the
        # handler's response write (review regression)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            snap = fe.stats_snapshot()
            if snap["http_responses"].get("200") == 1:
                break
            time.sleep(0.02)
        assert snap["http_responses"].get("504") == 1
        assert sum(snap["http_responses"].values()) == \
            snap["http_requests"]


def test_bad_requests_and_routes(model):
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        port = fe.bound_port
        for payload in ({"prompt": []}, {"prompt": [1], "nope": 1},
                        {"prompt": [999]}, {"prompt": "hi"},
                        {"prompt": [1], "grammar": {"type": "??"}},
                        None):
            status, _, body = http_request("127.0.0.1", port, "POST",
                                           "/v1/completions", payload)
            assert status == 400, payload
            assert "error" in body
        status, _, _ = http_request("127.0.0.1", port, "GET",
                                    "/nothing")
        assert status == 404
        status, _, _ = http_request("127.0.0.1", port, "GET",
                                    "/v1/completions")
        assert status == 405
        # exactly-once accounting holds for turned-away traffic too:
        # requests a 400/404/405 answers before a Request exists are
        # counted on BOTH sides (review regression: only parsed
        # completions were counted, so responses could exceed
        # requests and an error-rate dashboard read > 100%)
        snap = fe.stats_snapshot()
        assert snap["http_requests"] == 8
        assert sum(snap["http_responses"].values()) == \
            snap["http_requests"]


def test_malformed_content_length_gets_400(model):
    """Review regression: a non-numeric (or negative) Content-Length
    raised an uncaught ValueError that killed the connection task —
    the client saw a dropped connection instead of a 400."""
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        for bad in (b"abc", b"-5"):
            with socket.create_connection(
                    ("127.0.0.1", fe.bound_port), timeout=10) as sock:
                sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                             b"Content-Length: " + bad + b"\r\n\r\n")
                sock.settimeout(10)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                assert buf.startswith(b"HTTP/1.1 400"), (bad, buf[:60])
        snap = fe.stats_snapshot()
        assert snap["http_requests"] == 2
        assert sum(snap["http_responses"].values()) == 2


def test_partial_request_read_times_out(model):
    """Review regression: the read side is bounded like the write side
    — a client that sends half a request (slowloris) must get its
    connection closed after ``header_timeout_s``, not pin a connection
    task forever."""
    eng = _eng(model)
    with ServeFrontend(eng, header_timeout_s=0.3) as fe:
        with socket.create_connection(("127.0.0.1", fe.bound_port),
                                      timeout=10) as sock:
            sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                         b"Content-Length: 64\r\n\r\nhalf a body")
            sock.settimeout(10)
            assert sock.recv(1) == b""    # server gave up and closed
        snap = fe.stats_snapshot()
        assert snap["http_requests"] == 0    # never parsed, not counted


def test_seed_sampling_and_stop_over_http(model):
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        port = fe.bound_port
        payload = {"prompt": [7, 8, 9], "max_new_tokens": 10,
                   "temperature": 1.0, "seed": 42, "top_k": 12,
                   "top_p": 0.9, "repetition_penalty": 1.2,
                   "stream": False}
        _, _, a = http_request("127.0.0.1", port, "POST",
                               "/v1/completions", payload)
        _, _, b = http_request("127.0.0.1", port, "POST",
                               "/v1/completions", payload)
        assert a["tokens"] == b["tokens"]    # equal seed reproduces
        # stop sequence: take a bigram from the greedy stream, rerun
        # with it armed — truncated result, STOP outcome, and the
        # STREAMED tokens never include the retracted match
        _, _, ref = http_request(
            "127.0.0.1", port, "POST", "/v1/completions",
            {"prompt": [7, 8, 9], "max_new_tokens": 12,
             "stream": False})
        stop = ref["tokens"][5:7]
        # the match fires at the FIRST occurrence in the (repetitive)
        # greedy stream — compute where that actually is
        cut = next(i for i in range(len(ref["tokens"]) - 1)
                   if ref["tokens"][i:i + 2] == stop)
        out = stream_completion(
            "127.0.0.1", port,
            {"prompt": [7, 8, 9], "max_new_tokens": 12,
             "stop": [stop]})
        assert out["final"]["outcome"] == "STOP"
        assert out["final"]["tokens"] == ref["tokens"][:cut]
        assert out["tokens"] == ref["tokens"][:cut]  # holdback held
    eng.audit_pages()


def test_grammar_constrained_completion_over_http(model):
    eng = _eng(model, spec_k=3)
    sequences = [[1, 2, 3], [5, 6, 7, 8]]
    with ServeFrontend(eng) as fe:
        out = stream_completion(
            "127.0.0.1", fe.bound_port,
            {"prompt": [4, 4, 4], "max_new_tokens": 8, "eos_id": 9,
             "tier": "BATCH",
             "grammar": {"type": "choice", "sequences": sequences}})
    assert out["final"]["outcome"] == "EOS"
    assert out["final"]["tier"] == "BATCH"
    body = out["final"]["tokens"]
    assert body[:-1] in sequences and body[-1] == 9
    assert eng.decode_trace_count <= 1 and eng.verify_trace_count <= 1


def test_metrics_and_healthz(model):
    eng = _eng(model)
    with ServeFrontend(eng) as fe:
        port = fe.bound_port
        http_request("127.0.0.1", port, "POST", "/v1/completions",
                     {"prompt": [1, 2], "max_new_tokens": 4,
                      "stream": False})
        status, _, health = http_request("127.0.0.1", port, "GET",
                                         "/healthz")
        assert status == 200 and health["status"] == "ok"
        status, headers, text = http_request("127.0.0.1", port, "GET",
                                             "/metrics")
        assert status == 200
        text = text.decode() if isinstance(text, bytes) else text
        # backend snapshot AND frontend counters in one scrape
        assert "mxtpu_serve_requests_total" in text
        assert "mxtpu_serve_http_requests_total 1" in text
        assert 'mxtpu_serve_http_responses_total{status="200"} 1' \
            in text
        assert "mxtpu_serve_sse_tokens_total" in text


def test_client_edge_lands_on_flight_recorder(model):
    eng = _eng(model, recorder=None)         # fresh FlightRecorder
    with ServeFrontend(eng) as fe:
        port = fe.bound_port
        http_request("127.0.0.1", port, "POST", "/v1/completions",
                     {"prompt": [1, 2, 3], "max_new_tokens": 4,
                      "stream": False})
        out = stream_completion("127.0.0.1", port,
                                {"prompt": [4, 5, 6],
                                 "max_new_tokens": 48},
                                abort_after_tokens=1)
        assert out["aborted"]
        _wait_finished(fe, n=2)
    evs = eng.flight.events("frontend")
    by_type = {}
    for e in evs:
        by_type.setdefault(e.etype, []).append(e)
    assert len(by_type[EventType.SUBMIT]) == 2
    assert len(by_type[EventType.ADMIT]) == 2
    terms = by_type[EventType.TERMINAL]
    assert len(terms) == 2                   # exactly one per request
    assert len({e.request_id for e in terms}) == 2
    outcomes = {e.data["outcome"]: e for e in terms}
    assert outcomes["MAX_TOKENS"].data["http_status"] == 200
    cancelled = outcomes["CANCELLED"]
    assert cancelled.data["http_status"] == 499
    assert "disconnect" in cancelled.data["cause"]
