"""Auto-resume supervisor: run a training process to completion across
crashes, kills and hangs.

The PR-3 checkpoint subsystem made training state preemption-safe
(async snapshots, atomic commit, bit-exact capsule resume) — but a
checkpoint nobody restarts from is just a tombstone. ``Supervisor``
closes the loop for long preemptible-TPU runs: it launches the training
command as a child process and

  - **restarts on crash** (non-zero exit, or death by signal — a
    ``kill -9`` / OOM-kill / preemption): the training script is
    expected to restore from its latest committed checkpoint at
    startup (``CheckpointManager.restore()`` — the PR-3 contract), so
    a restart re-enters the run bit-exactly at the last commit;
  - **converts hangs into restarts**: a zero-progress wall-time
    watchdog (``hang_timeout_s``) watches a progress signal — a
    ``progress_file`` the training loop appends to, or the latest
    committed step under ``ckpt_dir`` — and SIGKILLs a child that
    stops advancing (a wedged collective, a dead data pipeline, a host
    stall) instead of letting it burn the reservation forever;
  - **bounds the retries**: ``max_restarts`` total restarts with
    exponential backoff (``backoff_s`` doubling to ``backoff_max_s``);
    an attempt that made observable progress resets the backoff — a
    crash-loop is distinguished from an occasional preemption. Past
    the bound the supervisor gives up LOUDLY with the attempt history.

The supervisor never reads training state itself — process boundaries
are the fault isolation (the whole point: a SIGKILL'd child cannot be
observed from inside). ``tools/train_chaos_bench.py``'s ``kill9`` and
``hang`` scenarios assert the end-to-end contract: a run killed twice
mid-training produces a final loss sequence BIT-IDENTICAL to an
uninterrupted run.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import time
from typing import List, Optional, Sequence

from ..base import MXNetError

__all__ = ["Supervisor", "SupervisorReport", "Attempt"]


@dataclasses.dataclass
class Attempt:
    """One child-process lifetime."""
    exit_code: Optional[int]      # None when hang-killed before exit
    term_signal: Optional[int]    # signal that killed the child, if any
    runtime_s: float
    reason: str                   # "completed" | "crash" | "hang_kill"
    progressed: bool              # progress signal advanced during it


@dataclasses.dataclass
class SupervisorReport:
    completed: bool
    restarts: int
    hang_kills: int
    attempts: List[Attempt]
    backoffs: List[float]         # scheduled sleep before each restart
    total_wall_s: float

    def summary(self) -> str:
        return (f"completed={self.completed} restarts={self.restarts} "
                f"hang_kills={self.hang_kills} "
                f"wall={self.total_wall_s:.2f}s attempts="
                + "; ".join(
                    f"[{a.reason} rc={a.exit_code} sig={a.term_signal} "
                    f"{a.runtime_s:.2f}s]" for a in self.attempts))


class Supervisor:
    """Run ``argv`` to completion across crashes.

    Parameters
    ----------
    argv : the training command (e.g. ``[sys.executable, "train.py"]``).
        Exit 0 is completion; anything else (including death by
        signal) is a crash to restart from.
    ckpt_dir : checkpoint root the child commits ``step_N`` dirs into —
        used as the default progress signal (latest committed step).
    progress_file : a file the training loop appends to (loss log,
        heartbeat); preferred progress signal when given (finer-grained
        than checkpoint commits).
    max_restarts : restart budget (crashes AND hang kills). 0 = run
        once, never restart.
    backoff_s / backoff_max_s : exponential restart backoff (doubles
        per consecutive unproductive attempt, reset by progress).
    hang_timeout_s : zero-progress wall-time watchdog; None disables.
    startup_grace_s : the FIRST watchdog deadline after each launch —
        a cold start (interpreter + jax init + checkpoint restore +
        recompiles) makes no observable progress for a while and must
        not read as a hang, or the supervisor kill-loops healthy
        children on a loaded host. Default: max(30 s, 5x the hang
        timeout). Once the attempt shows progress the normal
        ``hang_timeout_s`` clock applies.
    env : extra environment for the child (merged over ``os.environ``).
    """

    def __init__(self, argv: Sequence[str], ckpt_dir: Optional[str] = None,
                 progress_file: Optional[str] = None,
                 max_restarts: int = 5, backoff_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 hang_timeout_s: Optional[float] = None,
                 startup_grace_s: Optional[float] = None,
                 poll_s: float = 0.05, env: Optional[dict] = None,
                 stdout=None, stderr=None, recorder=None,
                 postmortem_dir: Optional[str] = None):
        if hang_timeout_s is not None and \
                ckpt_dir is None and progress_file is None:
            raise MXNetError(
                "hang_timeout_s needs a progress signal: pass ckpt_dir "
                "and/or progress_file")
        self.argv = list(argv)
        self.ckpt_dir = ckpt_dir
        self.progress_file = progress_file
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.hang_timeout_s = hang_timeout_s
        if startup_grace_s is None and hang_timeout_s is not None:
            startup_grace_s = max(30.0, 5.0 * hang_timeout_s)
        self.startup_grace_s = startup_grace_s
        self.poll_s = float(poll_s)
        self.env = dict(env or {})
        self.stdout = stdout
        self.stderr = stderr
        # flight recorder (events.py): every restart is an
        # event, and an exhausted budget dumps a postmortem naming the
        # supervised command — written next to the checkpoints by
        # default so the evidence survives the dead run
        from ..events import resolve_recorder
        self.flight = resolve_recorder(
            recorder, histograms=False,
            postmortem_dir=postmortem_dir or ckpt_dir)

    # ------------------------------------------------------------------ #
    def _progress_token(self):
        """A comparable snapshot of the progress signal; ``None`` when
        nothing observable exists yet (treated as 'no progress')."""
        if self.progress_file is not None:
            try:
                st = os.stat(self.progress_file)
                return ("file", st.st_mtime_ns, st.st_size)
            except OSError:
                return None
        if self.ckpt_dir is not None:
            from ..checkpoint import manifest as _manifest
            steps = _manifest.list_steps(self.ckpt_dir)
            return ("step", steps[-1]) if steps else None
        return None

    def _launch(self) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.env)
        # own session/process group: a launcher-style command that
        # spawned workers must die as a TREE on a hang kill — a
        # SIGKILL'd wrapper alone leaks wedged grandchildren that keep
        # holding devices (and ticking the progress signal)
        return subprocess.Popen(self.argv, env=env,
                                stdout=self.stdout, stderr=self.stderr,
                                start_new_session=True)

    @staticmethod
    def _kill_tree(proc: subprocess.Popen) -> None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)  # pgid == pid (setsid)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()

    # ------------------------------------------------------------------ #
    def run(self, raise_on_failure: bool = True) -> SupervisorReport:
        """Supervise until the child completes or the restart budget is
        exhausted. Returns the attempt history; with
        ``raise_on_failure`` (default) an exhausted budget raises
        ``MXNetError`` carrying the same history."""
        t_start = time.monotonic()
        attempts: List[Attempt] = []
        backoffs: List[float] = []
        restarts = 0
        hang_kills = 0
        backoff = self.backoff_s
        while True:
            t0 = time.monotonic()
            last_token = self._progress_token()
            last_change = t0
            progressed = False
            proc = self._launch()
            hang = False
            while proc.poll() is None:
                time.sleep(self.poll_s)
                if self.hang_timeout_s is None:
                    continue
                token = self._progress_token()
                now = time.monotonic()
                if token != last_token:
                    last_token = token
                    last_change = now
                    progressed = True
                    continue
                # a cold-starting attempt gets the startup grace; once
                # it has shown progress, the normal hang clock applies
                deadline = self.hang_timeout_s if progressed else \
                    max(self.hang_timeout_s, self.startup_grace_s or 0.0)
                if now - last_change > deadline:
                    # zero-progress watchdog: a hang becomes a restart
                    self._kill_tree(proc)
                    proc.wait()
                    hang = True
                    break
            rc = proc.returncode
            runtime = time.monotonic() - t0
            if not progressed and self._progress_token() != last_token:
                progressed = True
            if hang:
                hang_kills += 1
                attempts.append(Attempt(None, signal.SIGKILL, runtime,
                                        "hang_kill", progressed))
            elif rc == 0:
                attempts.append(Attempt(0, None, runtime, "completed",
                                        progressed))
                return SupervisorReport(
                    True, restarts, hang_kills, attempts, backoffs,
                    time.monotonic() - t_start)
            else:
                sig = -rc if rc is not None and rc < 0 else None
                attempts.append(Attempt(rc, sig, runtime, "crash",
                                        progressed))
            if progressed:
                backoff = self.backoff_s   # not a crash-loop: reset
            if restarts >= self.max_restarts:
                report = SupervisorReport(
                    False, restarts, hang_kills, attempts, backoffs,
                    time.monotonic() - t_start)
                from ..events import EventType
                self.flight.emit("supervisor",
                                 EventType.SUPERVISOR_GIVEUP,
                                 entity=self.argv[0],
                                 restarts=restarts,
                                 hang_kills=hang_kills)
                self.flight.postmortem(
                    "supervisor give-up", " ".join(self.argv)[:200],
                    context={"restarts": restarts,
                             "hang_kills": hang_kills,
                             "summary": report.summary()})
                if raise_on_failure:
                    raise MXNetError(
                        f"supervisor gave up after {restarts} restarts "
                        f"(max {self.max_restarts}): {report.summary()}")
                return report
            restarts += 1
            backoffs.append(backoff)
            from ..events import EventType
            last = attempts[-1]
            self.flight.emit("supervisor",
                             EventType.SUPERVISOR_RESTART,
                             entity=self.argv[0], restart=restarts,
                             reason=last.reason,
                             exit_code=last.exit_code,
                             term_signal=last.term_signal,
                             backoff_s=backoff,
                             progressed=last.progressed)
            time.sleep(backoff)
            backoff = min(backoff * 2.0, self.backoff_max_s)
