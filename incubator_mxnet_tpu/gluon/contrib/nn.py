"""Contrib layers (re-design of
`python/mxnet/gluon/contrib/nn/basic_layers.py` — file-level citation,
SURVEY.md caveat)."""

from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "PixelShuffle2D"]


class HybridConcurrent(HybridBlock):
    """Runs children on the same input, concatenates outputs on ``axis``
    (parity: contrib.nn.HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_call(self, x):
        from ... import ndarray as nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self._axis)

    def forward(self, x):
        return self.hybrid_call(x)


class Concurrent(HybridConcurrent):
    """Eager twin (parity: contrib.nn.Concurrent)."""


class Identity(HybridBlock):
    """Passes input through unchanged (parity: contrib.nn.Identity —
    useful as a no-op branch in Concurrent)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding with row_sparse gradients (parity:
    contrib.nn.SparseEmbedding). Sugar over
    ``nn.Embedding(sparse_grad=True)`` — the optimizer's lazy path
    touches only looked-up rows (optimizer.py _rows_update)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._emb = nn.Embedding(input_dim, output_dim, dtype=dtype,
                                     weight_initializer=weight_initializer,
                                     sparse_grad=True, prefix="")
        self.weight = self._emb.weight

    def hybrid_call(self, x):
        return self._emb(x)

    def forward(self, x):
        return self.hybrid_call(x)


class PixelShuffle2D(HybridBlock):
    """Rearranges (B, C*f1*f2, H, W) → (B, C, H*f1, W*f2) (parity:
    contrib.nn.PixelShuffle2D; sub-pixel convolution upsampling)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factors = (factor, factor) if isinstance(factor, int) \
            else tuple(factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        B, C, H, W = x.shape
        if C % (f1 * f2):
            raise MXNetError(
                f"PixelShuffle2D: channels {C} not divisible by "
                f"{f1}*{f2}")
        c = C // (f1 * f2)
        x = F.reshape(x, shape=(B, c, f1, f2, H, W))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return F.reshape(x, shape=(B, c, H * f1, W * f2))
