"""Auto-resume supervisor: bounded restarts, exponential backoff, the
zero-progress hang watchdog, and the end-to-end kill -9 resume contract
(docs/RESILIENCE.md "Training resilience").

The cheap units drive trivial non-jax children (fast, tier-1); the
full kill -9 training resume — loss sequence bit-identical to an
uninterrupted run — is the slow end-to-end test, also exercised every
CI run by the ``trainchaos`` stage (tools/train_chaos_bench.py).
"""

import json
import os
import sys
import textwrap

import pytest

from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.train import Supervisor


def _script(tmp_path, body):
    p = tmp_path / "child.py"
    p.write_text(textwrap.dedent(body))
    return [sys.executable, str(p)]


def test_completes_without_restart(tmp_path):
    sup = Supervisor(_script(tmp_path, "raise SystemExit(0)"),
                     max_restarts=3, backoff_s=0.01)
    report = sup.run()
    assert report.completed and report.restarts == 0
    assert [a.reason for a in report.attempts] == ["completed"]


def test_restarts_across_crashes_then_completes(tmp_path):
    # the child crashes until its scratch counter reaches 2
    argv = _script(tmp_path, f"""
        import os
        c = {str(tmp_path / "count")!r}
        n = int(open(c).read()) if os.path.exists(c) else 0
        open(c, "w").write(str(n + 1))
        raise SystemExit(0 if n >= 2 else 1)
    """)
    sup = Supervisor(argv, max_restarts=5, backoff_s=0.01)
    report = sup.run()
    assert report.completed and report.restarts == 2
    assert [a.reason for a in report.attempts] == \
        ["crash", "crash", "completed"]


def test_backoff_doubles_without_progress(tmp_path):
    prog = tmp_path / "progress"
    prog.write_text("static\n")
    sup = Supervisor(_script(tmp_path, "raise SystemExit(1)"),
                     progress_file=str(prog), max_restarts=3,
                     backoff_s=0.02, backoff_max_s=0.05)
    report = sup.run(raise_on_failure=False)
    assert not report.completed and report.restarts == 3
    # no progress ever observed -> exponential, capped
    assert report.backoffs == [0.02, 0.04, 0.05]


def test_backoff_resets_on_progress(tmp_path):
    # every attempt touches the progress file (real work happened)
    # before crashing — an occasional preemption, not a crash-loop
    prog = tmp_path / "progress"
    argv = _script(tmp_path, f"""
        import os
        p = {str(prog)!r}
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        raise SystemExit(0 if n >= 2 else 1)
    """)
    sup = Supervisor(argv, progress_file=str(prog), max_restarts=5,
                     backoff_s=0.02, hang_timeout_s=30.0)
    report = sup.run()
    assert report.completed and report.restarts == 2
    assert report.backoffs == [0.02, 0.02]   # reset each time


def test_hang_watchdog_converts_hang_into_restart(tmp_path):
    prog = tmp_path / "progress"
    argv = _script(tmp_path, f"""
        import os, time
        p = {str(prog)!r}
        m = {str(tmp_path / "hung_once")!r}
        if os.path.exists(m):
            raise SystemExit(0)        # healthy after the restart
        open(m, "w").write("x")
        open(p, "a").write("alive\\n") # one heartbeat, then wedge
        time.sleep(3600)
    """)
    sup = Supervisor(argv, progress_file=str(prog), max_restarts=2,
                     backoff_s=0.01, hang_timeout_s=0.5, poll_s=0.02)
    report = sup.run()
    assert report.completed
    assert report.hang_kills == 1 and report.restarts == 1
    assert report.attempts[0].reason == "hang_kill"


def test_gives_up_loudly_after_budget(tmp_path):
    sup = Supervisor(_script(tmp_path, "raise SystemExit(3)"),
                     max_restarts=2, backoff_s=0.01)
    with pytest.raises(MXNetError, match="gave up after 2 restarts"):
        sup.run()
    report = sup.run(raise_on_failure=False)
    assert not report.completed
    assert len(report.attempts) == 3
    assert all(a.exit_code == 3 for a in report.attempts)


def test_watchdog_requires_progress_signal(tmp_path):
    with pytest.raises(MXNetError, match="progress signal"):
        Supervisor([sys.executable, "-c", "pass"], hang_timeout_s=1.0)


def test_death_by_signal_is_a_crash(tmp_path):
    argv = _script(tmp_path, f"""
        import os, signal
        m = {str(tmp_path / "killed")!r}
        if os.path.exists(m):
            raise SystemExit(0)
        open(m, "w").write("x")
        os.kill(os.getpid(), signal.SIGKILL)
    """)
    sup = Supervisor(argv, max_restarts=2, backoff_s=0.01)
    report = sup.run()
    assert report.completed and report.restarts == 1
    import signal as _sig
    assert report.attempts[0].term_signal == _sig.SIGKILL


# --------------------------------------------------------------------- #
# end-to-end: kill -9 a real training run twice; the resumed loss
# sequence must be bit-identical to an uninterrupted run's
# --------------------------------------------------------------------- #

def _run_target(tmp_path, tag, steps, kill_at="", max_restarts=0,
                hang_timeout_s=None):
    ckpt = tmp_path / f"ckpt_{tag}"
    results = tmp_path / f"results_{tag}.jsonl"
    ckpt.mkdir()
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "MXTPU_TGT_CKPT_DIR": str(ckpt),
        "MXTPU_TGT_RESULTS": str(results),
        "MXTPU_TGT_STEPS": str(steps),
        "MXTPU_TGT_SAVE_EVERY": "2",
        "MXTPU_TGT_KILL_AT": kill_at,
    }
    sup = Supervisor(
        [sys.executable, "-m", "incubator_mxnet_tpu.train.example_target"],
        ckpt_dir=str(ckpt), progress_file=str(results),
        max_restarts=max_restarts, backoff_s=0.05,
        hang_timeout_s=hang_timeout_s, env=env)
    report = sup.run()
    by_step = {}
    with open(results) as f:
        for line in f:
            rec = json.loads(line)
            by_step[rec["step"]] = rec["loss"]
    return report, by_step


@pytest.mark.slow
def test_kill9_twice_resumes_bit_exact(tmp_path):
    steps = 14
    _, clean = _run_target(tmp_path, "clean", steps)
    report, survived = _run_target(tmp_path, "killed", steps,
                                   kill_at="5,9", max_restarts=4)
    assert report.completed
    assert report.restarts == 2
    assert sorted(a.reason for a in report.attempts) == \
        ["completed", "crash", "crash"]
    assert set(survived) == set(clean) == set(range(steps))
    for s in range(steps):
        assert survived[s] == clean[s], \
            f"loss diverged at step {s}: {survived[s]} != {clean[s]}"
    # backoff honored between restarts (scheduled, not timing-flaky)
    assert len(report.backoffs) == 2
    assert all(b >= 0.05 for b in report.backoffs)
