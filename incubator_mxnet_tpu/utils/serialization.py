"""NDArray / parameter serialization.

Two on-disk formats, auto-detected on load by magic:

**MXTPU v1** (the native format) — little-endian
    8 bytes  magic  b'MXTPU\\x00\\x01\\x00'
    8 bytes  header length N (uint64)
    N bytes  JSON header: {"names": [...], "arrays": [{dtype, shape}, ...]}
    raw buffers, each 64-byte aligned, in header order (C-contiguous)

**MXNet 1.x ``.params``** (migration compat; SURVEY §5.4 "keep .params
read/write compat as a migration tool") — the reference's binary layout
(`NDArray::Save/Load` in `src/ndarray/ndarray.cc` + the list container
in `MXNDArrayListSave`, file-level citations, SURVEY.md caveat;
implemented from the public format since the reference mount is empty —
byte-level fixtures in tests/test_serialization_mxnet.py pin it down):
    uint64  0x112 (kMXAPINDArrayListMagic)
    uint64  0 (reserved)
    uint64  array count, then per array:
        uint32  0xF993fac9 (NDARRAY_V2_MAGIC; V3 0xF993faca also read)
        int32   storage type (0 = dense; sparse records are rejected)
        uint32  ndim, then int64 × ndim shape
        int32   dev_type, int32 dev_id (written 1,0 = cpu; ignored on read)
        int32   mshadow type flag (0 f32, 1 f64, 2 f16, 3 u8, 4 i32,
                5 i8, 6 i64, 7 bool, 12 bf16)
        raw C-order little-endian buffer
    uint64  name count, then per name: uint64 length + utf-8 bytes

Arrays are always materialized on host before save (the reference strips
device too); load returns host arrays that callers place onto devices.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Union

import jax
import numpy as np

from ..base import MXNetError

MAGIC = b"MXTPU\x00\x01\x00"
_ALIGN = 64

# MXNet 1.x constants (src/ndarray/ndarray.cc / c_api.cc, file-level)
_MX_LIST_MAGIC = 0x112
_NDARRAY_V2_MAGIC = 0xF993FAC9
_NDARRAY_V3_MAGIC = 0xF993FACA
_MX_DENSE_STYPE = 0
# mshadow type flags <-> numpy/ml_dtypes names
_MX_TYPE_FLAGS = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                  "int32": 4, "int8": 5, "int64": 6, "bool": 7,
                  "bfloat16": 12}
_MX_FLAG_NAMES = {v: k for k, v in _MX_TYPE_FLAGS.items()}


def _tohost(arr) -> np.ndarray:
    if hasattr(arr, "_data"):
        arr = arr._data
    out = np.asarray(jax.device_get(arr))
    # bfloat16 has no numpy dtype string repr numpy understands natively in
    # all versions; store via uint16 view with a marker.
    return out


def _dtype_str(a: np.ndarray) -> str:
    return str(a.dtype)


def _to_bytes(a: np.ndarray) -> bytes:
    """C-order raw buffer; bfloat16 goes through a uint16 view (numpy
    can't serialize the ml_dtypes dtype directly)."""
    a = np.ascontiguousarray(a)
    if a.dtype.name == "bfloat16":
        a = a.view(np.uint16)
    return a.tobytes(order="C")


def save_ndarrays(fname: str, data, format: str = "mxtpu") -> None:
    """Save a dict/list of NDArrays. ``format="mxnet"`` writes the
    reference's 1.x ``.params`` binary layout for migration."""
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [_tohost(v) for v in data.values()]
        named = True
    elif isinstance(data, (list, tuple)):
        names = [str(i) for i in range(len(data))]
        arrays = [_tohost(v) for v in data]
        named = False
    else:
        names = ["0"]
        arrays = [_tohost(data)]
        named = False

    if format == "mxnet":
        _save_mxnet(fname, names if named else [], arrays)
        return
    if format != "mxtpu":
        raise MXNetError(f"unknown params format {format!r} "
                         f"(want 'mxtpu' or 'mxnet')")

    metas = []
    bufs = []
    for a in arrays:
        name = "bfloat16" if a.dtype.name == "bfloat16" else _dtype_str(a)
        metas.append({"dtype": name, "shape": list(a.shape)})
        bufs.append(_to_bytes(a))

    header = json.dumps({"names": names, "arrays": metas}).encode("utf-8")
    with open(fname, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        pos = len(MAGIC) + 8 + len(header)
        for buf in bufs:
            padding = (-pos) % _ALIGN
            f.write(b"\x00" * padding)
            pos += padding
            f.write(buf)
            pos += len(buf)


def _np_for_flag(flag: int, fname: str):
    name = _MX_FLAG_NAMES.get(flag)
    if name is None:
        raise MXNetError(f"{fname}: unsupported mshadow type flag {flag}")
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _save_mxnet(fname: str, names: List[str], arrays) -> None:
    """Write the reference ``.params`` list container (dense only)."""
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQQ", _MX_LIST_MAGIC, 0, len(arrays)))
        for a in arrays:
            name = ("bfloat16" if a.dtype.name == "bfloat16"
                    else str(a.dtype))
            flag = _MX_TYPE_FLAGS.get(name)
            if flag is None:
                raise MXNetError(
                    f"dtype {name} has no MXNet 1.x type flag; save in "
                    f"the native format instead")
            f.write(struct.pack("<Ii", _NDARRAY_V2_MAGIC,
                                _MX_DENSE_STYPE))
            f.write(struct.pack("<I", a.ndim))
            f.write(struct.pack(f"<{a.ndim}q", *a.shape))
            f.write(struct.pack("<ii", 1, 0))  # cpu ctx, stripped on load
            f.write(struct.pack("<i", flag))
            f.write(_to_bytes(a))
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _read_exact(f, n, fname):
    raw = f.read(n)
    if len(raw) != n:
        raise MXNetError(f"{fname}: truncated .params file")
    return raw


def _load_mxnet(fname: str):
    """Read the reference ``.params`` list container (dense V2/V3)."""
    from ..ndarray import NDArray
    import jax.numpy as jnp

    with open(fname, "rb") as f:
        magic, _reserved, count = struct.unpack(
            "<QQQ", _read_exact(f, 24, fname))
        assert magic == _MX_LIST_MAGIC
        arrays = []
        for _ in range(count):
            (nd_magic,) = struct.unpack("<I", _read_exact(f, 4, fname))
            if nd_magic not in (_NDARRAY_V2_MAGIC, _NDARRAY_V3_MAGIC):
                raise MXNetError(
                    f"{fname}: pre-V2 (legacy) NDArray record "
                    f"0x{nd_magic:x} not supported; re-save with a "
                    f"MXNet >= 1.3 build")
            (stype,) = struct.unpack("<i", _read_exact(f, 4, fname))
            if stype != _MX_DENSE_STYPE:
                raise MXNetError(
                    f"{fname}: sparse storage type {stype} not "
                    f"supported by the migration loader")
            (ndim,) = struct.unpack("<I", _read_exact(f, 4, fname))
            shape = struct.unpack(
                f"<{ndim}q", _read_exact(f, 8 * ndim, fname))
            struct.unpack("<ii", _read_exact(f, 8, fname))  # ctx dropped
            (flag,) = struct.unpack("<i", _read_exact(f, 4, fname))
            dt = _np_for_flag(flag, fname)
            n_items = int(np.prod(shape)) if shape else 1
            raw = _read_exact(f, n_items * dt.itemsize, fname)
            arrays.append(np.frombuffer(raw, dtype=dt).reshape(shape))
        (n_names,) = struct.unpack("<Q", _read_exact(f, 8, fname))
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack("<Q", _read_exact(f, 8, fname))
            names.append(_read_exact(f, ln, fname).decode("utf-8"))
    if names and len(names) != len(arrays):
        raise MXNetError(f"{fname}: {len(arrays)} arrays but "
                         f"{len(names)} names")
    # narrow 64-bit records explicitly (framework is 32-bit, x64 off) so
    # jnp.asarray doesn't emit a truncation warning per array — but
    # never silently wrap values the narrow type can't hold
    narrowed = []
    for a in arrays:
        if a.dtype == np.int64:
            if a.size and (a.max() > np.iinfo(np.int32).max
                           or a.min() < np.iinfo(np.int32).min):
                raise MXNetError(
                    f"{fname}: int64 record holds values outside the "
                    f"int32 range; the 32-bit runtime cannot represent "
                    f"them losslessly")
            a = a.astype(np.int32)
        elif a.dtype == np.float64:
            a = a.astype(np.float32)  # precision loss only, as on TPU
        narrowed.append(a)
    arrays = narrowed
    out = [NDArray(jnp.asarray(a)) for a in arrays]
    if not names:
        return out
    return dict(zip(names, out))


def load_ndarrays(fname: str):
    """Returns dict name→NDArray (or list if names are all indices).
    Format auto-detected: native MXTPU, reference ``.params``, or a
    checkpoint-capsule blob (its ``param/``-prefixed entries are
    returned keyed by Parameter name, so ``collect_params().load``-style
    consumers can open training capsules too)."""
    from ..ndarray import NDArray
    import jax.numpy as jnp
    import ml_dtypes

    with open(fname, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            if (len(magic) == 8
                    and struct.unpack("<Q", magic)[0] == _MX_LIST_MAGIC):
                return _load_mxnet(fname)
            from ..checkpoint import capsule as _capsule
            if magic == _capsule.CAPSULE_MAGIC:
                arrays, meta = _capsule.load_capsule_file(fname)
                names = meta.get("param_names") or []
                out = {}
                for key, a in arrays.items():
                    if not key.startswith("param/"):
                        continue
                    idx = key[len("param/"):]
                    name = names[int(idx)] \
                        if idx.isdigit() and int(idx) < len(names) else idx
                    out[name] = NDArray(jnp.asarray(a))
                if not out:   # capsule without params: expose raw entries
                    out = {k: NDArray(jnp.asarray(v))
                           for k, v in arrays.items()}
                return out
            raise MXNetError(
                f"{fname}: neither a MXTPU params file nor a MXNet 1.x "
                f".params file")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode("utf-8"))
        pos = len(MAGIC) + 8 + hlen
        out = {}
        for name, meta in zip(header["names"], header["arrays"]):
            padding = (-pos) % _ALIGN
            f.read(padding)
            pos += padding
            shape = tuple(meta["shape"])
            if meta["dtype"] == "bfloat16":
                count = int(np.prod(shape)) if shape else 1
                raw = f.read(count * 2)
                pos += len(raw)
                arr = np.frombuffer(raw, dtype=np.uint16).reshape(shape) \
                    .view(ml_dtypes.bfloat16)
            else:
                dt = np.dtype(meta["dtype"])
                count = int(np.prod(shape)) if shape else 1
                raw = f.read(count * dt.itemsize)
                pos += len(raw)
                arr = np.frombuffer(raw, dtype=dt).reshape(shape)
            out[name] = NDArray(jnp.asarray(arr))
    if out and all(k.isdigit() for k in out):
        return [out[str(i)] for i in range(len(out))]
    return out
