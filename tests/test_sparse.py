"""Sparse storage tests (reference strategy:
tests/python/unittest/test_sparse_ndarray.py — numpy oracles, stype
round-trips, sparse optimizer/kvstore flows)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.ndarray import sparse


def test_row_sparse_roundtrip():
    data = np.array([[1., 2.], [3., 4.]], np.float32)
    rsp = sparse.row_sparse_array((data, [1, 3]), shape=(5, 2))
    assert rsp.stype == "row_sparse"
    assert rsp.nnz == 2
    dense = rsp.asnumpy()
    expect = np.zeros((5, 2), np.float32)
    expect[1], expect[3] = data[0], data[1]
    np.testing.assert_array_equal(dense, expect)
    # dense -> rsp -> dense
    back = nd.array(expect).tostype("row_sparse")
    assert back.nnz == 2
    np.testing.assert_array_equal(back.asnumpy(), expect)
    np.testing.assert_array_equal(back.tostype("default").asnumpy(), expect)


def test_csr_roundtrip():
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
    csr = nd.array(dense).tostype("csr")
    assert csr.stype == "csr"
    assert csr.nnz == 3
    np.testing.assert_array_equal(csr.asnumpy(), dense)
    np.testing.assert_array_equal(np.asarray(csr.indptr.asnumpy()),
                                  [0, 1, 3, 3])
    # explicit construction
    c2 = sparse.csr_matrix((csr.data.asnumpy(), csr.indices.asnumpy(),
                            csr.indptr.asnumpy()), shape=(3, 3))
    np.testing.assert_array_equal(c2.asnumpy(), dense)


def test_sparse_zeros_and_retain():
    z = sparse.zeros("row_sparse", (4, 3))
    assert z.nnz == 0 and z.asnumpy().sum() == 0
    rsp = sparse.row_sparse_array(
        (np.ones((3, 2), np.float32), [0, 2, 3]), shape=(5, 2))
    kept = sparse.retain(rsp, [2, 3])
    assert kept.nnz == 2
    assert kept.asnumpy()[0].sum() == 0


def test_sparse_dot():
    rng = np.random.RandomState(0)
    dense = (rng.rand(4, 6) > 0.5) * rng.randn(4, 6)
    dense = dense.astype(np.float32)
    csr = nd.array(dense).tostype("csr")
    rhs = rng.randn(6, 3).astype(np.float32)
    got = sparse.dot(csr, nd.array(rhs)).asnumpy()
    np.testing.assert_allclose(got, dense @ rhs, rtol=1e-5, atol=1e-5)
    gotT = sparse.dot(csr, nd.array(rng.randn(4, 2).astype(np.float32)),
                      transpose_a=True)
    assert gotT.shape == (6, 2)


def test_sparse_array_scipy_like():
    import scipy.sparse as sps
    m = sps.random(5, 4, density=0.4, format="csr", dtype=np.float32,
                   random_state=0)
    arr = sparse.array(m)
    np.testing.assert_allclose(arr.asnumpy(), m.toarray(), rtol=1e-6)


def test_lazy_sgd_update_touches_only_active_rows():
    w = nd.array(np.ones((6, 3), np.float32))
    grad = sparse.row_sparse_array(
        (np.full((2, 3), 0.5, np.float32), [1, 4]), shape=(6, 3))
    opt = mx.optimizer.SGD(learning_rate=1.0, lazy_update=True)
    state = opt.create_state(0, w)
    opt.update(0, w, grad, state)
    out = w.asnumpy()
    np.testing.assert_allclose(out[1], 0.5)   # 1 - 1.0*0.5
    np.testing.assert_allclose(out[4], 0.5)
    np.testing.assert_allclose(out[0], 1.0)   # untouched rows
    np.testing.assert_allclose(out[5], 1.0)


def test_lazy_adam_matches_dense_on_active_rows():
    rng = np.random.RandomState(1)
    w0 = rng.randn(5, 2).astype(np.float32)
    g_rows = rng.randn(2, 2).astype(np.float32)
    g_dense = np.zeros((5, 2), np.float32)
    g_dense[[0, 3]] = g_rows

    w_sparse = nd.array(w0)
    opt_s = mx.optimizer.Adam(learning_rate=0.1, lazy_update=True)
    st_s = opt_s.create_state(0, w_sparse)
    opt_s.update(0, w_sparse,
                 sparse.row_sparse_array((g_rows, [0, 3]), shape=(5, 2)),
                 st_s)

    w_dense = nd.array(w0)
    opt_d = mx.optimizer.Adam(learning_rate=0.1)
    st_d = opt_d.create_state(0, w_dense)
    opt_d.update(0, w_dense, nd.array(g_dense), st_d)

    # active rows identical; inactive rows untouched in the sparse path
    ws, wd = w_sparse.asnumpy(), w_dense.asnumpy()
    np.testing.assert_allclose(ws[[0, 3]], wd[[0, 3]], rtol=1e-5)
    np.testing.assert_allclose(ws[[1, 2, 4]], w0[[1, 2, 4]], rtol=1e-6)


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    table = np.arange(20, dtype=np.float32).reshape(10, 2)
    kv.init("emb", nd.array(table))
    out = sparse.zeros("row_sparse", (10, 2))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array([2.0, 7.0]))
    assert out.nnz == 2
    np.testing.assert_array_equal(out.data.asnumpy(), table[[2, 7]])
    dense_out = nd.zeros((10, 2))
    kv.row_sparse_pull("emb", out=dense_out, row_ids=nd.array([1.0]))
    got = dense_out.asnumpy()
    np.testing.assert_array_equal(got[1], table[1])
    assert got[[0, 2]].sum() == 0


def test_embedding_sparse_grad_training():
    """gluon Embedding(sparse_grad=True): only looked-up rows change."""
    emb = gluon.nn.Embedding(20, 4, sparse_grad=True)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    tr = gluon.Trainer(emb.collect_params(), "sgd",
                       {"learning_rate": 1.0})
    idx = nd.array(np.array([3.0, 7.0, 3.0]))
    with autograd.record():
        out = emb(idx)
        loss = (out ** 2).sum()
    loss.backward()
    tr.step(1)
    w1 = emb.weight.data().asnumpy()
    changed = np.abs(w1 - w0).sum(axis=1) > 1e-7
    assert changed[3] and changed[7]
    assert changed.sum() == 2  # every other row untouched


def test_row_sparse_pull_dedups_and_sorts():
    kv = mx.kv.create("local")
    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    kv.init("t", nd.array(table))
    out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("t", out=out, row_ids=nd.array([5.0, 2.0, 2.0]))
    np.testing.assert_array_equal(out.indices.asnumpy(), [2, 5])
    np.testing.assert_array_equal(out.data.asnumpy(), table[[2, 5]])
