"""ctypes binding for the native JPEG decode engine
(src/io/image_decode_native.cc).

Batched, GIL-free decode + bilinear resize on a C++ thread pool — the
TPU-native counterpart of the reference's decode threads in
src/io/iter_image_recordio_2.cc. Auto-builds with the sibling IO
library; callers must handle ``lib() is None`` (no toolchain / no
libjpeg) and fall back to cv2.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "lib",
                         "libmxtpu_image.so")


def lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_LIB_PATH):
            if os.environ.get("MXTPU_NO_NATIVE"):
                return None
            try:
                subprocess.run(["make", "-C", _SRC_DIR, "image"],
                               check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            l = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        l.mxtpu_img_dims.restype = ctypes.c_int
        l.mxtpu_img_dims.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        l.mxtpu_img_decode_batch.restype = ctypes.c_int
        l.mxtpu_img_decode_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_int]
        _LIB = l
        return _LIB


def decode_batch(payloads: Sequence[bytes], out_h: int, out_w: int,
                 n_threads: int = 0) -> Optional[np.ndarray]:
    """Decode JPEG byte strings to (N, out_h, out_w, 3) uint8 RGB with
    bilinear resize, on a C++ thread pool. None when the native lib is
    unavailable; raises ValueError on a malformed payload."""
    l = lib()
    if l is None or not payloads:
        return None if l is None else np.zeros((0, out_h, out_w, 3),
                                               np.uint8)
    blob = b"".join(payloads)
    lengths = np.array([len(p) for p in payloads], np.int64)
    offsets = np.zeros(len(payloads), np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    out = np.empty((len(payloads), out_h, out_w, 3), np.uint8)
    if n_threads <= 0:
        n_threads = min(len(payloads), os.cpu_count() or 4)
    rc = l.mxtpu_img_decode_batch(
        blob, offsets.ctypes.data_as(ctypes.c_void_p),
        lengths.ctypes.data_as(ctypes.c_void_p), len(payloads),
        out_h, out_w, out.ctypes.data_as(ctypes.c_void_p), n_threads)
    if rc != 0:
        raise ValueError(
            f"native JPEG decode failed for batch item {-rc - 1}")
    return out


def image_dims(payload: bytes):
    """(width, height) of a JPEG without a full decode; None when the
    native lib is unavailable; raises ValueError on malformed input."""
    l = lib()
    if l is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    if l.mxtpu_img_dims(payload, len(payload), ctypes.byref(w),
                        ctypes.byref(h)) != 0:
        raise ValueError("native JPEG header parse failed")
    return w.value, h.value
