"""Transformer NMT on a toy copy/reverse task + jitted beam search
(BASELINE.md config #4; reference: GluonNLP `scripts/nmt` train_transformer
— file-level citation, SURVEY.md caveat).

The task: translate a random token sequence to its REVERSE. Small enough
to train in ~a minute on CPU, while exercising the full encoder-decoder
stack, label smoothing, and the fixed-shape beam-search decode.

    python examples/nmt_toy_copy.py --steps 120
"""

import argparse

import numpy as np

import _common  # noqa: F401  (accelerator-or-CPU bootstrap)

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu.models.transformer import (TransformerModel,
                                                    beam_search_translate)

PAD, BOS, EOS = 0, 1, 2
VOCAB = 32
SEQ = 8


def batch(rng, n):
    src = rng.randint(3, VOCAB, (n, SEQ))
    tgt = src[:, ::-1].copy()
    tgt_in = np.concatenate([np.full((n, 1), BOS), tgt[:, :-1]], axis=1)
    return (src.astype(np.int32), tgt_in.astype(np.int32),
            tgt.astype(np.int32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    model = TransformerModel(src_vocab=VOCAB, tgt_vocab=VOCAB,
                             units=64, hidden_size=128, num_heads=4,
                             num_layers=2, max_length=SEQ + 4)
    model.initialize()
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr}, kvstore="device")
    lf = gluon.loss.SoftmaxCrossEntropyLoss()

    for step in range(args.steps):
        src, tgt_in, tgt = batch(rng, 32)
        with autograd.record():
            logits = model(nd.array(src), nd.array(tgt_in))
            L = lf(logits.reshape((-1, VOCAB)),
                   nd.array(tgt.reshape(-1))).mean()
        L.backward()
        trainer.step(1)
        if step % 30 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(L.asnumpy()):.4f}")

    # beam-search decode and measure exact-reversal accuracy
    src, _, tgt = batch(rng, 16)
    toks, scores = beam_search_translate(model, nd.array(src), beam_size=4,
                                         max_length=SEQ + 2, bos_id=BOS,
                                         eos_id=EOS)
    best = toks.asnumpy()[:, 0, :SEQ]
    acc = float((best == tgt).mean())
    print(f"beam-search token accuracy on reverse task: {acc:.3f}")


if __name__ == "__main__":
    main()
