"""Imperative op dispatch: registry function → eager NDArray call.

The analogue of the reference's generated-op + invoke path
(`python/mxnet/ndarray/register.py` → `MXImperativeInvokeEx` →
`Imperative::Invoke`, SURVEY.md §3.1; file-level citations, SURVEY caveat).

The entire call stack of the reference's hot path (Python → C ABI → engine
queue → worker thread → kernel launch) collapses to: unwrap ``jax.Array``s,
call the op's pure function (XLA dispatches asynchronously), wrap outputs,
and — when autograd is recording — append one tape node.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.tree_util as jtu

from .. import autograd, random as _random
from ..base import MXNetError
from ..ops import registry as _reg
from .ndarray import NDArray, _as_jax

__all__ = ["imperative_invoke", "invoke_by_name", "make_op_function"]


def _is_leaf(x):
    return isinstance(x, NDArray)


def imperative_invoke(spec: _reg.OpSpec, *args, out=None, ctx=None, **kwargs):
    """Execute a registered op eagerly on NDArray inputs."""
    # resolve mode-dependent statics at call time (dropout/batchnorm)
    if spec.training_aware and kwargs.get("training") is None:
        kwargs["training"] = autograd.is_training()
    # stochastic ops: thread a fresh key from the global stream as an
    # input — EXCEPT training-aware ops outside training (inert dropout):
    # they would burn a key they never use, and inside a jax trace the
    # split would store a TRACER into the global stream (leaking it out
    # of the transform and corrupting every later RNG call)
    if spec.needs_key and kwargs.get("key") is None and not (
            spec.training_aware and not kwargs.get("training")
            and kwargs.get("mode", "training") != "always"):
        kwargs["key"] = _random.new_key()
    key_arr = kwargs.pop("key", None)

    # flatten args AND kwargs together so NDArrays passed by keyword
    # (e.g. ``sequence_length=``) are unwrapped and autograd-visible too
    flat, treedef = jtu.tree_flatten((args, kwargs), is_leaf=_is_leaf)
    arr_pos: List[int] = []
    primals: List[Any] = []
    owners: List[Any] = []
    for i, leaf in enumerate(flat):
        if isinstance(leaf, NDArray):
            arr_pos.append(i)
            primals.append(leaf._data)
            owners.append(leaf)
        elif isinstance(leaf, jax.Array):
            arr_pos.append(i)
            primals.append(leaf)
            owners.append(None)
    if key_arr is not None:
        if isinstance(key_arr, NDArray):
            key_arr = key_arr._data
        primals.append(key_arr)
        owners.append(None)

    n_args = len(primals) - (1 if key_arr is not None else 0)

    def pure_fn(*arrs):
        flat2 = list(flat)
        for pos, a in zip(arr_pos, arrs[:n_args]):
            flat2[pos] = a
        call_args, call_kwargs = jtu.tree_unflatten(treedef, flat2)
        if key_arr is not None:
            res = spec.fn(*call_args, key=arrs[-1], **call_kwargs)
        else:
            res = spec.fn(*call_args, **call_kwargs)
        # normalize variadic outputs to a tuple so vjp seeding is uniform
        return tuple(res) if isinstance(res, list) else res

    try:
        result = pure_fn(*primals)
    except (TypeError, ValueError) as e:
        raise MXNetError(f"operator {spec.name} failed: {e}") from e

    multi = isinstance(result, (tuple, list))
    if ctx is not None:
        dev = ctx.jax_device
        result = jax.device_put(result, dev)
    outs = [NDArray(r) for r in (result if multi else (result,))]

    if autograd.is_recording():
        autograd._record_node(pure_fn, primals, owners, outs, name=spec.name,
                              tuple_out=multi)

    # NaiveEngine debug mode: surface async errors at the faulting op
    # (parity: MXNET_ENGINE_TYPE=NaiveEngine — SURVEY.md §5.2)
    from .. import engine as _engine
    if _engine.is_sync():
        _engine._maybe_sync(outs)

    if out is not None:
        targets = out if isinstance(out, (tuple, list)) else (out,)
        for t, o in zip(targets, outs):
            t._data = o._data.astype(t.dtype)
        return out
    return outs if multi else outs[0]


def invoke_by_name(name: str, *args, **kwargs):
    return imperative_invoke(_reg.get(name), *args, **kwargs)


def make_op_function(spec: _reg.OpSpec, public_name: str):
    """Build the module-level function surfaced as ``mx.nd.<name>``."""

    def op_function(*args, **kwargs):
        return imperative_invoke(spec, *args, **kwargs)

    op_function.__name__ = public_name
    op_function.__qualname__ = public_name
    op_function.__doc__ = _reg.describe_op(spec.name)
    return op_function
