"""Data iterators (re-design of `python/mxnet/io/io.py` + the native iters
of `src/io/` — SURVEY.md §2.1 Data I/O row, §3.5 call stack)."""

from __future__ import annotations

import collections
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _as_jax
from . import recordio
from .recordio import MXRecordIO, IndexedRecordIO, MXIndexedRecordIO, \
    pack, unpack, pack_img, \
    unpack_img, IRHeader

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ImageRecordIter", "ImageDetRecordIter",
           "MNISTIter", "ResizeIter", "PrefetchingIter", "recordio"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape", "dtype",
                                                   "layout"])):
    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)


class DataBatch:
    """(parity: mx.io.DataBatch)"""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None, bucket_key=None):
        self.data = data if isinstance(data, (list, tuple)) else [data]
        self.label = label if label is None or isinstance(label, (list, tuple)) \
            else [label]
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label
        if bucket_key is not None:  # BucketingModule routing (parity)
            self.bucket_key = bucket_key


class DataIter:
    """Epoch-based iterator (parity: mx.io.DataIter), extended with the
    position-export contract the checkpoint capsule records
    (docs/CHECKPOINTING.md): ``tell()`` returns a JSON-able dict,
    ``set_position(state)`` restores it so resumed training replays the
    exact remaining batch sequence."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    # -- resumable-position contract (checkpoint capsule) ----------- #
    def tell(self) -> dict:
        """Exportable position. Subclasses without one refuse loudly so
        a capsule never silently records a non-resumable iterator."""
        raise MXNetError(
            f"{type(self).__name__} does not support position export; "
            f"wrap data in NDArrayIter or add tell()/set_position()")

    def set_position(self, state: dict):
        raise MXNetError(
            f"{type(self).__name__} does not support position restore")

    def __next__(self):
        return self.next()

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _draw_shuffle_seed() -> int:
    """One int from the global RNG stream (same stream position cost as
    the np_rng() the shuffles previously consumed) — the recorded seed
    makes an epoch's shuffle order reproducible from O(1) state."""
    import jax
    from .. import random as _random
    return int(jax.device_get(_random.new_key())[0]) & 0x7FFFFFFF


def _to_nd_list(arrs) -> List[NDArray]:
    if arrs is None:
        return []
    if isinstance(arrs, (np.ndarray, NDArray)):
        arrs = [arrs]
    if isinstance(arrs, dict):
        arrs = list(arrs.values())
    return [a if isinstance(a, NDArray) else NDArray(_as_jax(a))
            for a in arrs]


class NDArrayIter(DataIter):
    """Batches over in-memory arrays (parity: mx.io.NDArrayIter), with
    pad/discard/roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self._data = _to_nd_list(data)
        self._label = _to_nd_list(label)
        self._names = [data_name] if len(self._data) == 1 else \
            [f"{data_name}{i}" for i in range(len(self._data))]
        self._label_names = [label_name] if len(self._label) == 1 else \
            [f"{label_name}{i}" for i in range(len(self._label))]
        self._shuffle = shuffle
        self._last = last_batch_handle
        self.num_data = self._data[0].shape[0] if self._data else 0
        self._order = np.arange(self.num_data)
        self._shuffle_seed = None
        self._cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], str(d.dtype))
                for n, d in zip(self._names, self._data)]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], str(d.dtype))
                for n, d in zip(self._label_names, self._label)]

    def reset(self):
        if self._shuffle:
            # seed-recorded shuffle of a FRESH arange: the epoch order
            # is then a pure function of one int, so tell() exports it
            # O(1) instead of serializing the whole permutation into
            # every checkpoint (millions of ints of JSON at scale)
            self._shuffle_seed = _draw_shuffle_seed()
            self._order = np.arange(self.num_data)
            np.random.RandomState(self._shuffle_seed).shuffle(self._order)
        # roll_over: a short tail is not emitted at epoch end; its samples
        # are prepended to the first batch of the next epoch (reference
        # NDArrayIter contract)
        leftover = self.num_data - self._cursor
        if self._last == "roll_over" and 0 < leftover < self.batch_size:
            self._cursor = -leftover - self.batch_size
        else:
            self._cursor = -self.batch_size

    def iter_next(self):
        self._cursor += self.batch_size
        if self._last in ("discard", "roll_over"):
            return self._cursor + self.batch_size <= self.num_data
        return self._cursor < self.num_data

    def _slice(self, arrs):
        import jax.numpy as jnp
        start = max(self._cursor, 0)
        end = min(self._cursor + self.batch_size, self.num_data)
        idx = self._order[start:end]
        if self._cursor < 0:  # roll_over head: prepend last epoch's tail
            idx = np.concatenate([self._order[self._cursor:], idx])
        pad = self.batch_size - len(idx)
        if pad > 0 and self._last == "pad":
            idx = np.concatenate([idx, self._order[:pad]])
        return [NDArray(jnp.take(a._data, jnp.asarray(idx), axis=0))
                for a in arrs]

    def getdata(self):
        return self._slice(self._data)

    def getlabel(self):
        return self._slice(self._label)

    def getpad(self):
        end = self._cursor + self.batch_size
        if self._last == "pad" and end > self.num_data:
            return end - self.num_data
        return 0

    def tell(self) -> dict:
        # the epoch's shuffle seed travels with the cursor (O(1) state)
        # so a mid-epoch resume re-derives the same remaining samples
        return {"cursor": int(self._cursor), "num_data": self.num_data,
                "shuffle_seed": self._shuffle_seed}

    def set_position(self, state: dict):
        if state.get("num_data") is not None and \
                int(state["num_data"]) != self.num_data:
            raise MXNetError(
                f"iterator position is for {state['num_data']} samples, "
                f"this iterator has {self.num_data}")
        if state.get("shuffle_seed") is not None:
            self._shuffle_seed = int(state["shuffle_seed"])
            self._order = np.arange(self.num_data)
            np.random.RandomState(self._shuffle_seed).shuffle(self._order)
        self._cursor = int(state["cursor"])


class CSVIter(DataIter):
    """CSV reader (parity: mx.io.CSVIter, reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((data.shape[0], 1), np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def tell(self) -> dict:
        return self._inner.tell()

    def set_position(self, state: dict):
        self._inner.set_position(state)


class LibSVMIter(DataIter):
    """LibSVM text-format reader emitting CSR data batches (parity:
    mx.io.LibSVMIter, reference src/io/iter_libsvm.cc). Lines are
    ``label idx:val idx:val ...`` (0-based indices, the reference's
    convention). The dataset is held as one CSR triple and sliced per
    batch — never densified."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self._feat_dim = int(data_shape[0] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        labels, data, indices, indptr = [], [], [], [0]
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for p in parts[1:]:
                    k, v = p.split(":")
                    indices.append(int(k))
                    data.append(float(v))
                indptr.append(len(data))
        self._data = np.asarray(data, np.float32)
        self._indices = np.asarray(indices, np.int32)
        self._indptr = np.asarray(indptr, np.int64)
        self._num = len(labels)
        label = np.asarray(labels, np.float32).reshape(-1, 1)
        if label_libsvm is not None:
            ldim = int(label_shape[0] if isinstance(
                label_shape, (tuple, list)) else (label_shape or 1))
            llabels = []
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    lrow = np.zeros(ldim, np.float32)
                    for p in parts[1:]:
                        k, v = p.split(":")
                        lrow[int(k)] = float(v)
                    llabels.append(lrow)
            label = np.asarray(llabels, np.float32)
        self._label = label
        self._round = round_batch
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._feat_dim),
                         np.float32)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label",
                         (self.batch_size,) + self._label.shape[1:],
                         np.float32)]

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self._num

    def tell(self) -> dict:
        return {"cursor": int(self._cursor)}

    def set_position(self, state: dict):
        self._cursor = int(state["cursor"])

    def _rows(self, lo, hi):
        """CSR slice [lo, hi) as an (batch_size, feat_dim) CSRNDArray;
        short final batches pad with empty rows (round_batch)."""
        from ..ndarray.sparse import csr_matrix
        sl = slice(self._indptr[lo], self._indptr[hi])
        indptr = self._indptr[lo:hi + 1] - self._indptr[lo]
        pad = self.batch_size - (hi - lo)
        if pad:
            indptr = np.concatenate(
                [indptr, np.full(pad, indptr[-1], np.int64)])
        return csr_matrix(
            (self._data[sl], self._indices[sl], indptr),
            shape=(self.batch_size, self._feat_dim))

    def next(self):
        if not self.iter_next():
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._num)
        if not self._round and hi - lo < self.batch_size:
            raise StopIteration
        self._cursor = hi
        lab = self._label[lo:hi]
        pad = self.batch_size - (hi - lo)
        if pad:
            lab = np.concatenate(
                [lab, np.zeros((pad,) + lab.shape[1:], np.float32)])
        return DataBatch(data=[self._rows(lo, hi)],
                         label=[NDArray(_as_jax(lab))], pad=pad)


class MNISTIter(DataIter):
    """(parity: mx.io.MNISTIter, reference src/io/iter_mnist.cc)"""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, seed=0, silent=False, num_parts=1, part_index=0,
                 **kwargs):
        super().__init__(batch_size)
        from ..gluon.data.vision.datasets import MNIST
        import os
        root = os.path.dirname(image) if image else "~/.mxnet/datasets/mnist"
        train = image is None or "train" in os.path.basename(image)
        try:
            ds = MNIST(root=root, train=train)
            imgs = ds._data
            labels = ds._label
        except MXNetError:
            ds = MNIST(root=root, train=train, synthetic=True)
            imgs = ds._data
            labels = ds._label
        imgs = imgs.astype(np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.transpose(0, 3, 1, 2)  # NCHW
        if num_parts > 1:
            imgs = imgs[part_index::num_parts]
            labels = labels[part_index::num_parts]
        self._inner = NDArrayIter(imgs, labels.astype(np.float32), batch_size,
                                  shuffle=shuffle,
                                  last_batch_handle="discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def tell(self) -> dict:
        return self._inner.tell()

    def set_position(self, state: dict):
        self._inner.set_position(state)


class ImageRecordIter(DataIter):
    """RecordIO image iterator (parity: mx.io.ImageRecordIter, reference
    `src/io/iter_image_recordio_2.cc`).

    Uses the native reader (src/) when available for GIL-free batched file
    IO; decode+augment run in Python. Supports shuffle, partitioning
    (num_parts/part_index for multi-host), HWC→NCHW, mean/std, rand_crop
    and rand_mirror augmentation.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, num_parts=1, part_index=0, preprocess_threads=4,
                 round_batch=True, seed=0, **kwargs):
        super().__init__(batch_size)
        self._path = path_imgrec
        self._shape = tuple(data_shape)  # (C, H, W)
        self._label_width = label_width
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self._std = np.array([std_r, std_g, std_b], np.float32)
        self._round = round_batch

        self._native = None
        try:
            from ._native import NativeRecordReader
            self._native = NativeRecordReader(path_imgrec,
                                              n_threads=preprocess_threads)
            n = len(self._native)
        except Exception:
            self._plain = MXRecordIO(path_imgrec, "r")
            self._offsets = []
            while True:
                pos = self._plain.tell()
                if self._plain.read() is None:
                    break
                self._offsets.append(pos)
            n = len(self._offsets)
        idx = np.arange(n)
        if num_parts > 1:
            idx = idx[part_index::num_parts]
        self._indices = idx
        self._order = np.array(idx)
        self._shuffle_seed = None
        self.reset()

    def reset(self):
        if self._shuffle:
            self._shuffle_seed = _draw_shuffle_seed()
            self._order = np.array(self._indices)
            np.random.RandomState(self._shuffle_seed).shuffle(self._order)
        self._cursor = 0

    def _read_records(self, ids):
        if self._native is not None:
            return self._native.read_batch(ids)
        out = []
        for i in ids:
            self._plain.seek(self._offsets[i])
            out.append(self._plain.read())
        return out

    def _decode(self, payload):
        header, img = unpack_img(payload)
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        C, H, W = self._shape
        from .. import random as _random
        if img.ndim == 2:
            img = img[:, :, None]
        if self._rand_crop and (img.shape[0] > H or img.shape[1] > W):
            rng = _random.np_rng()
            y0 = rng.randint(0, img.shape[0] - H + 1)
            x0 = rng.randint(0, img.shape[1] - W + 1)
            img = img[y0:y0 + H, x0:x0 + W]
        elif img.shape[0] != H or img.shape[1] != W:
            y0 = max((img.shape[0] - H) // 2, 0)
            x0 = max((img.shape[1] - W) // 2, 0)
            img = img[y0:y0 + H, x0:x0 + W]
        if img.shape[0] < H or img.shape[1] < W:
            # upsize smaller-than-target images by edge replication so every
            # decoded sample stacks to exactly data_shape (the reference
            # resizes via OpenCV; edge-pad is the hermetic equivalent)
            img = np.pad(img, ((0, max(H - img.shape[0], 0)),
                               (0, max(W - img.shape[1], 0)), (0, 0)),
                         mode="edge")
        if self._rand_mirror and _random.np_rng().rand() < 0.5:
            img = img[:, ::-1]
        img = img.astype(np.float32)
        if img.shape[2] >= 3:
            img = (img - self._mean) / self._std
        return img.transpose(2, 0, 1), label[:self._label_width]

    def iter_next(self):
        return self._cursor < len(self._order)

    def tell(self) -> dict:
        return {"cursor": int(self._cursor),
                "num_records": len(self._indices),
                "shuffle_seed": self._shuffle_seed}

    def set_position(self, state: dict):
        if state.get("num_records") is not None and \
                int(state["num_records"]) != len(self._indices):
            raise MXNetError(
                f"iterator position is for {state['num_records']} "
                f"records, this record set has {len(self._indices)}")
        if state.get("shuffle_seed") is not None:
            self._shuffle_seed = int(state["shuffle_seed"])
            self._order = np.array(self._indices)
            np.random.RandomState(self._shuffle_seed).shuffle(self._order)
        self._cursor = int(state["cursor"])

    def next(self):
        if not self.iter_next():
            raise StopIteration
        end = self._cursor + self.batch_size
        ids = self._order[self._cursor:end].tolist()
        pad = 0
        if len(ids) < self.batch_size:
            if not self._round:
                self._cursor = len(self._order)
                if not ids:
                    raise StopIteration
            else:
                pad = self.batch_size - len(ids)
                ids = ids + self._order[:pad].tolist()
        self._cursor = end
        payloads = self._read_records(ids)
        imgs, labels = zip(*(self._decode(p) for p in payloads))
        import jax.numpy as jnp
        data = NDArray(jnp.asarray(np.stack(imgs)))
        label = NDArray(jnp.asarray(np.stack(labels).squeeze(-1)
                                    if self._label_width == 1
                                    else np.stack(labels)))
        return DataBatch([data], [label], pad=pad)


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches
    (parity: mx.io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self._iter = data_iter
        self._size = size
        self._reset_internal = reset_internal
        self._cur = 0

    def reset(self):
        self._cur = 0
        if self._reset_internal:
            self._iter.reset()

    def next(self):
        if self._cur >= self._size:
            raise StopIteration
        self._cur += 1
        try:
            return self._iter.next()
        except StopIteration:
            self._iter.reset()
            return self._iter.next()

    def tell(self) -> dict:
        return {"cur": int(self._cur), "inner": self._iter.tell()}

    def set_position(self, state: dict):
        self._cur = int(state["cur"])
        if state.get("inner") is not None:
            self._iter.set_position(state["inner"])


class PrefetchingIter(DataIter):
    """Background-thread prefetch wrapper (parity: mx.io.PrefetchingIter,
    reference dmlc ThreadedIter double-buffering).

    Resumable: the producer thread runs AHEAD of the consumer, so the
    inner iterator's own position is meaningless mid-stream; instead
    the wrapper counts batches actually DELIVERED to the consumer.
    ``set_position`` resets the inner iterator and replays that many
    batches before restarting the prefetch thread — O(position) on
    resume, zero overhead on the hot path.

    Failure surface (docs/RESILIENCE.md "Training resilience"): every
    producer-side error — including ``BaseException`` and silent thread
    death — PROPAGATES to the consumer instead of hanging it on an
    empty queue forever; ``next()`` polls the producer's liveness with
    a bounded timeout and raises loudly if it died without delivering
    a batch, an error, or the end-of-stream sentinel. TRANSIENT read
    errors (``OSError`` — an NFS blip, a flaky fuse mount) are retried
    with bounded exponential backoff (``MXTPU_IO_RETRY_ATTEMPTS``,
    default 3 attempts; ``MXTPU_IO_RETRY_BACKOFF`` base delay, default
    0.05 s, doubling) before propagating. ``MXTPU_IO_FAIL_READS=n``
    fault-injects n transient failures (one per read attempt) for the
    chaos harness: n under the attempt bound still delivers every
    batch; n at/over it fails exactly as a persistent outage would."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        it = iters[0] if isinstance(iters, (list, tuple)) else iters
        super().__init__(it.batch_size)
        self._iter = it
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = object()
        self._thread = None
        self._cancel = None
        self._exhausted = False
        self._delivered = 0
        # stats shared between the producer thread and consumer-side
        # scrapers: guarded so a retry bump on the producer cannot be
        # lost to a torn read-modify-write (mxlint lock-discipline)
        import threading as _threading
        self._lock = _threading.Lock()
        self.read_retries = 0           # transient-IO retry count
        self._injected_failures = 0     # MXTPU_IO_FAIL_READS bookkeeping
        self._epoch_start = self._try_tell()
        self._start()

    def _try_tell(self):
        """The inner iterator's position at the point the producer
        starts — replayed on resume so a shuffled inner iterator
        re-walks the SAME epoch order instead of reshuffling."""
        try:
            return self._iter.tell()
        except MXNetError:
            return None

    def _maybe_inject_read_failure(self):
        """``MXTPU_IO_FAIL_READS=n``: the first n read ATTEMPTS raise a
        transient OSError — the deterministic fault the retry loop is
        tested against (the CheckpointManager writer's twin)."""
        import os as _os
        budget = int(_os.environ.get("MXTPU_IO_FAIL_READS", "0") or 0)
        with self._lock:
            if self._injected_failures >= budget:
                return
            self._injected_failures += 1
            count = self._injected_failures
        raise OSError(
            f"injected transient data-iterator read failure "
            f"({count}/{budget})")

    def _next_inner(self):
        """One inner read with bounded exponential-backoff retry on
        TRANSIENT IO errors (OSError); StopIteration and structural
        errors propagate untouched."""
        import os as _os
        import time as _time
        attempts = max(1, int(_os.environ.get(
            "MXTPU_IO_RETRY_ATTEMPTS", "3") or 3))
        backoff = float(_os.environ.get(
            "MXTPU_IO_RETRY_BACKOFF", "0.05") or 0.05)
        for attempt in range(attempts):
            try:
                self._maybe_inject_read_failure()
                return self._iter.next()
            except StopIteration:
                raise
            except OSError:
                if attempt + 1 >= attempts:
                    raise
                with self._lock:
                    self.read_retries += 1
                # cancel-aware backoff: a reset() mid-retry must abort
                # the sleep promptly, not trip the bounded-join timeout
                # on a healthy (merely recovering) producer
                if self._cancel is not None and \
                        self._cancel.wait(backoff * (2 ** attempt)):
                    raise

    def _safe_put(self, item, cancel) -> bool:
        """Bounded put that aborts promptly when reset() cancels;
        returns False if cancelled before delivery."""
        import queue as _queue
        while not cancel.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _start(self):
        import threading

        cancel = threading.Event()

        def run():
            while not cancel.is_set():
                try:
                    batch = self._next_inner()
                except StopIteration:
                    break
                except BaseException as e:   # noqa: B036 — the consumer
                    # must see EVERY producer death, KeyboardInterrupt/
                    # SystemExit included; swallowing one would hang
                    # next() forever
                    self._safe_put(e, cancel)
                    self._safe_put(self._stop, cancel)
                    return
                if not self._safe_put(batch, cancel):
                    return
            self._safe_put(self._stop, cancel)

        # the producer only reads _cancel, and the write lands before
        # Thread.start publishes it to the new thread
        # mxlint: allow-lock-discipline(set before Thread.start, happens-before)
        self._cancel = cancel
        self._exhausted = False
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def _stop_producer(self):
        # cancel the old producer FIRST, then drain so its pending put
        # unblocks; only one thread ever touches self._iter at a time.
        # The drain is BOUNDED (MXTPU_IO_JOIN_TIMEOUT, default 30 s —
        # generous enough for a slow remote read to finish and notice
        # the cancel, which is only polled between reads) so a producer
        # wedged inside the inner iterator's C/IO cannot hang reset()
        # forever; past the bound we refuse to reuse the iterator.
        import os as _os
        import time as _time
        self._cancel.set()
        limit = float(_os.environ.get("MXTPU_IO_JOIN_TIMEOUT", "30")
                      or 30)
        deadline = _time.monotonic() + limit
        while self._thread.is_alive():
            if _time.monotonic() > deadline:
                raise MXNetError(
                    f"PrefetchingIter producer thread did not stop "
                    f"within {limit:g} s (MXTPU_IO_JOIN_TIMEOUT) — "
                    f"inner iterator wedged; cannot safely reuse it")
            try:
                self._queue.get(timeout=0.1)
            except Exception:
                pass
        self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()

    def reset(self):
        self._stop_producer()
        self._iter.reset()
        self._delivered = 0
        self._epoch_start = self._try_tell()
        self._start()

    def next(self):
        import queue as _queue
        if self._exhausted:
            raise StopIteration
        while True:
            try:
                item = self._queue.get(timeout=0.2)
                break
            except _queue.Empty:
                if self._thread is not None and self._thread.is_alive():
                    continue            # producer just slow — keep waiting
                try:                    # died after a final put? drain it
                    item = self._queue.get_nowait()
                    break
                except _queue.Empty:
                    self._exhausted = True
                    raise MXNetError(
                        "PrefetchingIter producer thread died without "
                        "delivering a batch, an error, or end-of-stream "
                        "— propagating instead of hanging the consumer")
        if item is self._stop:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._exhausted = True
            raise item
        self._delivered += 1
        return item

    def tell(self) -> dict:
        if self._epoch_start is None:
            # without the inner's epoch-start state, a resume would
            # reset() the inner (reshuffling it) and replay a DIFFERENT
            # sample order — refuse loudly rather than record a
            # position that silently diverges
            raise MXNetError(
                f"PrefetchingIter over "
                f"{type(self._iter).__name__} is not resumable: the "
                f"inner iterator does not support tell()")
        return {"delivered": int(self._delivered),
                "epoch_start": self._epoch_start}

    def set_position(self, state: dict):
        n = int(state["delivered"])
        self._stop_producer()
        self._iter.set_position(state["epoch_start"])
        self._epoch_start = state["epoch_start"]
        for _ in range(n):          # replay up to the delivered batch
            try:
                self._iter.next()
            except StopIteration:
                raise MXNetError(
                    f"cannot restore PrefetchingIter position "
                    f"{n}: inner iterator exhausted early")
        self._delivered = n
        self._exhausted = False
        self._start()


def ImageDetRecordIter(**kwargs):
    """Detection RecordIO iterator (parity surface: mx.io.
    ImageDetRecordIter) — delegates to image.ImageDetIter, translating
    the reference's kwargs (mean_r/g/b -> mean tuple, std_*, resize)
    and dropping its engine-tuning knobs (preprocess_threads etc.,
    meaningless here)."""
    from ..image.detection import ImageDetIter
    mean = tuple(kwargs.pop(f"mean_{c}", 0.0) for c in "rgb")
    std = tuple(kwargs.pop(f"std_{c}", 1.0) for c in "rgb")
    passthrough = {}
    for k in ("batch_size", "data_shape", "path_imgrec", "shuffle",
              "max_objects", "aug_list", "resize", "rand_crop",
              "rand_mirror"):
        if k in kwargs:
            passthrough[k] = kwargs.pop(k)
    if any(mean):
        passthrough["mean"] = mean
    if std != (1.0, 1.0, 1.0):
        passthrough["std"] = std
    # remaining reference knobs (label_width, preprocess_threads,
    # label_pad_width, ...) tune the C++ pipeline; ignored here
    return ImageDetIter(**passthrough)
