"""Page transport: KV pages and live slots as network-mobile resources.

The paged KV layout makes a page the natural unit of transfer between
replicas — the same per-page payload the cache tiers demote to host
DRAM/disk (``engine.gather_page``: int8/fp8 codes + one f32 scale per
layer on quantized pools, the 4x-denser wire form; raw dtype otherwise)
is also a wire format. This module builds two things on that
observation:

**PageCapsule** — a slot's pages plus everything else the slot IS
(emitted tokens, pinned RNG key, sampling/grammar/stop state — all
resumable-as-data since the failover work), checksummed page-by-page
with a CHAINED crc32: each page's crc seeds the next
(``paged_kv.payload_crc``), so a dropped, reordered, or substituted
page breaks every later link, not just its own. ``verify()`` re-walks
the chain; ``corrupt()`` is the public fault-injection seam the chaos
harness uses to model wire bit rot.

**PageTransport** — the capture/install protocol between two engines:

- ``capture(engine, request_id)``: gather the decode-ready slot's
  pages through the ONE jitted gather program (shared with tier
  demotion — a capture never compiles anything), then DETACH the slot
  into the source engine's in-capsule custody. Capture is read-only
  until every page is on the host: an abort mid-capture (source death)
  leaves the source slot exactly as it was — the replay fallback
  re-queues nothing here, the death path owns that.
- ``install(engine, capsule, request)``: verify the chain, then write
  every payload through the ONE jitted promotion program (shared with
  tier re-admission) into fresh private pages on the destination. The
  installed slot resumes with only the boundary token recomputed (its
  logits must seed the next sample — the wire cannot carry logits), so
  a migration redoes ZERO prefill tokens; the continuation is
  bit-identical to the never-migrated stream because the destination
  runs exactly the resume-from-suffix path replay already runs, minus
  the recompute.

Every failure mode — crc mismatch, wire-signature mismatch, abort
mid-install, no capacity — degrades to the always-correct replay
fallback (the router re-queues from the client's delivered suffix),
loudly, never silently: migration is an optimisation over replay,
and the correctness story never depends on it succeeding.

The capsule's ``_records``/``_chain_crc`` internals are off-limits
outside this module — the mxlint page-refcount pass enforces it, the
same way tier-store and allocator internals are fenced.
"""

import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from .engine import InferenceEngine, Request
from .paged_kv import payload_crc, payload_nbytes

__all__ = ["PageCapsule", "PageTransport"]


class PageCapsule:
    """One slot's wire image: page payloads under a chained crc32 plus
    the slot identity (request state, pinned RNG key, position). Built
    page-by-page by ``PageTransport.capture``; consumed whole by
    ``install``. The payload records are private — everything a
    consumer needs goes through ``verify``/``payloads``/``nbytes``."""

    def __init__(self, request_id: int, wire_sig: tuple, n_pos: int,
                 key: np.ndarray):
        self.request_id = int(request_id)
        self.wire_sig = tuple(wire_sig)
        self.n_pos = int(n_pos)          # captured KV positions [0, n_pos)
        self.key = np.asarray(key, np.uint32)   # the slot's PINNED
        # sampling key: an engine-drawn key exists nowhere else, so it
        # MUST travel or the destination would re-draw from its own
        # stream and the continuation would silently diverge
        self.request: Optional[Request] = None  # the detached attempt,
        # set when capture completes (tokens + sampling + budget ride
        # on it — resumable-as-data)
        self._records: List[Tuple] = []  # (k, v, kamax, vamax, chain)
        self._chain_crc = 0

    @property
    def num_pages(self) -> int:
        return len(self._records)

    @property
    def nbytes(self) -> int:
        """Wire bytes: what ``kv_migrated_bytes_total`` counts —
        quantized pools ship ~1/4 the raw-dtype bytes."""
        return sum(payload_nbytes(k, v, ka, va)
                   for k, v, ka, va, _ in self._records)

    @property
    def crc(self) -> int:
        return self._chain_crc

    def add_page(self, k_payload, v_payload, kamax, vamax) -> None:
        """Append one page payload, extending the crc chain: this
        page's crc is seeded by every page before it."""
        self._chain_crc = payload_crc(k_payload, v_payload, kamax,
                                      vamax, seed=self._chain_crc)
        self._records.append((k_payload, v_payload, kamax, vamax,
                              self._chain_crc))

    def verify(self) -> bool:
        """Re-walk the chain from zero: every page's recomputed chain
        value must equal the one recorded at capture. A single flipped
        bit fails its own page AND every page after it."""
        c = 0
        for k, v, ka, va, chain in self._records:
            c = payload_crc(k, v, ka, va, seed=c)
            if c != chain:
                return False
        return True

    def payloads(self) -> List[Tuple]:
        """The page payloads in chain order, verified — raises on a
        broken chain so no caller can install bytes the chain does not
        vouch for."""
        if not self.verify():
            raise MXNetError(
                f"capsule for request {self.request_id}: crc chain "
                f"broken — refusing to expose payloads")
        return [(k, v, ka, va) for k, v, ka, va, _ in self._records]

    def corrupt(self, page_idx: int = 0, byte: int = 0) -> None:
        """Fault-injection seam: flip one payload byte WITHOUT
        updating the recorded chain — the capsule now models a capsule
        that took wire bit rot. The chaos harness's corrupt-crc
        scenario is this call; production code never uses it."""
        k, v, ka, va, chain = self._records[page_idx]
        k0 = np.array(k[0])              # writable copy
        flat = k0.view(np.uint8).reshape(-1)
        flat[byte % flat.size] ^= 0xFF
        self._records[page_idx] = ((k0,) + tuple(k[1:]), v, ka, va,
                                   chain)

    def make_resume_request(self) -> Optional[Request]:
        """Build the destination attempt from the capsule's carried
        state — prompt = everything the source knew (original prompt +
        every emitted token), budget = what remains, ``prompt_len``
        marking the true-prompt split so grammar/stop state re-derive
        from the generated suffix only. The capsule's pinned key rides
        as ``_assigned_key`` so a seedless stream continues
        bit-identically. None when the deadline already passed (the
        caller owns that terminal)."""
        r = self.request
        if r is None:
            raise MXNetError("capsule was never detached from its "
                             "source — no request state to resume")
        if r.token_ids:
            prompt = np.concatenate(
                [r.prompt_ids, np.asarray(r.token_ids, np.int32)])
        else:
            prompt = r.prompt_ids.copy()
        deadline = None
        if r._deadline_abs is not None:
            deadline = r._deadline_abs - time.perf_counter()
            if deadline <= 0:
                return None
        att = Request(prompt,
                      max_new_tokens=(r.max_new_tokens -
                                      len(r.token_ids)),
                      temperature=r.temperature, eos_id=r.eos_id,
                      deadline_s=deadline, seed=r.seed, tier=r.tier,
                      sampling=r.sampling,
                      prompt_len=(r.prompt_len if r.prompt_len
                                  is not None
                                  else int(r.prompt_ids.size)))
        att._assigned_key = np.asarray(self.key, np.uint32)
        return att


class PageTransport:
    """The capture/install protocol (module docstring). Holds the
    chaos seams — per-page hooks and abort predicates on both sides,
    plus the capsule (wire) hook — and the protocol counters. One
    transport instance serves a whole fleet; it keeps no per-transfer
    state between calls."""

    def __init__(self):
        self.captures = 0
        self.installs = 0
        self.capture_failures = 0
        self.install_failures = 0
        # chaos seams (serve/chaos.py): called per page during
        # capture/install; the abort predicates model a replica dying
        # mid-transfer, the capsule hook models the wire itself
        self._capture_hook: Optional[Callable[[int, int], None]] = None
        self._install_hook: Optional[Callable[[int, int], None]] = None
        self._capture_abort: Optional[Callable[[], bool]] = None
        self._install_abort: Optional[Callable[[], bool]] = None
        self._capsule_hook: Optional[Callable[[PageCapsule], None]] = \
            None

    def capture(self, engine: InferenceEngine,
                request_id: int) -> Optional[PageCapsule]:
        """Capture ``request_id``'s decode-ready slot off ``engine``
        into a capsule. Read-only until the last page is on the host;
        only then is the slot detached into in-capsule custody — an
        abort anywhere before that returns None with the source slot
        UNTOUCHED (still decoding; the replay fallback owes nothing).
        On success the source engine's slot is gone, its pages held in
        custody until ``engine.release_capsule(request_id)``."""
        probe = engine.capture_slot(request_id)
        if probe is None:
            self.capture_failures += 1
            return None
        capsule = PageCapsule(request_id=request_id,
                              wire_sig=engine.kv_wire_sig(),
                              n_pos=probe["n_pos"], key=probe["key"])
        pages = probe["pages"]
        for j, page in enumerate(pages):
            if self._capture_hook is not None:
                self._capture_hook(j, len(pages))
            if self._capture_abort is not None and \
                    self._capture_abort():
                self.capture_failures += 1
                return None              # pre-detach: slot intact
            capsule.add_page(*engine.gather_page(page))
        req = engine.detach_slot(request_id)
        if req is None:                  # raced a terminal/evict
            self.capture_failures += 1
            return None
        capsule.request = req
        engine.migrated_out_pages += capsule.num_pages
        engine.migrated_out_bytes += capsule.nbytes
        self.captures += 1
        if self._capsule_hook is not None:
            self._capsule_hook(capsule)  # the wire (chaos bit rot)
        return capsule

    def install(self, engine: InferenceEngine, capsule: PageCapsule,
                request: Request) -> bool:
        """Install ``capsule`` into ``engine`` as ``request``'s slot.
        Refuses — False, destination untouched or fully rolled back —
        on a wire-signature mismatch, a broken crc chain, no capacity,
        or a mid-install abort. The caller owns the fallback (replay)
        and the source-side custody release either way."""
        if tuple(capsule.wire_sig) != tuple(engine.kv_wire_sig()):
            self.install_failures += 1
            return False
        if not capsule.verify():
            self.install_failures += 1
            return False
        ok = engine.install_slot(
            request, capsule.payloads(), capsule.n_pos, capsule.key,
            wire_bytes=capsule.nbytes, page_hook=self._install_hook,
            abort=self._install_abort)
        if ok:
            self.installs += 1
        else:
            self.install_failures += 1
        return ok
