"""Device-mesh construction and multi-host bootstrap.

Replaces the reference's launcher/tracker bootstrap (`tools/launch.py` +
dmlc tracker env `DMLC_ROLE`/`DMLC_PS_ROOT_URI` — SURVEY.md §3.4): there are
no scheduler/server processes; every host runs the same SPMD program and
`jax.distributed.initialize` forms the global device set.

Axis-name convention (used across models/ and spmd.py):
    dp    data parallelism (batch sharding; grads reduced over it)
    fsdp  parameter sharding fused with dp (ZeRO-style)
    tp    tensor/model parallelism (attention heads, MLP hidden)
    sp    sequence/context parallelism (ring attention)
    pp    pipeline stages (reserved)
    ep    expert parallelism (MoE; reserved)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")

_DEFAULT_MESH: Optional[Mesh] = None


@dataclasses.dataclass
class MeshConfig:
    """Sizes per logical axis; unspecified axes get size 1 and axes set to
    -1 absorb the remaining devices (at most one -1)."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXES}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise MXNetError(f"at most one mesh axis may be -1, got {wild}")
        fixed = 1
        for a, s in sizes.items():
            if s != -1:
                if s <= 0:
                    raise MXNetError(f"mesh axis {a} must be positive or -1")
                fixed *= s
        if wild:
            if n_devices % fixed != 0:
                raise MXNetError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise MXNetError(
                    f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None,
               axis_sizes: Optional[Dict[str, int]] = None) -> Mesh:
    """Build a `jax.sharding.Mesh` over ``devices`` (default: all).

    ``axis_sizes`` is shorthand: ``build_mesh(axis_sizes={'dp': 2, 'tp': 4})``.
    Axis order is the canonical ``AXES`` order with size-1 axes kept, so a
    PartitionSpec can always name any logical axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if config is None:
        config = MeshConfig(**(axis_sizes or {}))
    sizes = config.resolve(n)
    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, AXES)


def set_default_mesh(mesh: Optional[Mesh]):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def default_mesh() -> Mesh:
    """The process-default mesh (all devices on ``dp``) unless overridden."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = build_mesh()
    return _DEFAULT_MESH


def current_mesh() -> Optional[Mesh]:
    """The innermost mesh activated via ``with mesh:`` or None."""
    try:
        env_mesh = jax.sharding.get_abstract_mesh()  # jax>=0.4.35
    except Exception:
        env_mesh = None
    if env_mesh is not None and not getattr(env_mesh, "empty", True):
        return env_mesh
    return _DEFAULT_MESH


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Multi-host bootstrap (replaces `tools/launch.py` + dmlc tracker,
    SURVEY.md §3.4). Reads ``MXTPU_COORDINATOR``/``MXTPU_NUM_PROCS``/
    ``MXTPU_PROC_ID`` when args are omitted; no-op when single-process."""
    coordinator_address = coordinator_address or os.environ.get(
        "MXTPU_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
    if process_id is None:
        process_id = int(os.environ.get("MXTPU_PROC_ID", "0"))
    if num_processes <= 1 or coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
