"""NDArray semantics tests (ports the *behavioral contract* of the
reference's tests/python/unittest/test_ndarray.py — SURVEY.md §4)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import assert_almost_equal, with_seed


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert str(a.dtype) == "float32"
    b = nd.ones((4,), dtype="int32")
    assert b.asnumpy().tolist() == [1, 1, 1, 1]
    c = nd.full((2, 2), 7.0)
    assert c.asnumpy().max() == 7.0
    d = nd.array([[1, 2], [3, 4]])
    assert str(d.dtype) == "float32"  # python lists default to float32
    e = nd.array(np.int64(np.arange(4)).reshape(2, 2))
    assert "int" in str(e.dtype)
    f = nd.arange(0, 10, 2)
    assert f.shape == (5,)
    g = nd.eye(3)
    assert g.asnumpy()[1, 1] == 1.0


def test_arithmetic_broadcast():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([10.0, 20.0])
    assert_almost_equal((a + b).asnumpy(), np.array([[11, 22], [13, 24]]))
    assert_almost_equal((a * 2 + 1).asnumpy(), a.asnumpy() * 2 + 1)
    assert_almost_equal((1 - a).asnumpy(), 1 - a.asnumpy())
    assert_almost_equal((a / b).asnumpy(), a.asnumpy() / b.asnumpy())
    assert_almost_equal((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert_almost_equal((-a).asnumpy(), -a.asnumpy())
    assert_almost_equal(abs(nd.array([-1.0, 2.0])).asnumpy(), [1.0, 2.0])


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 1
    assert a.asnumpy().min() == 2.0
    a *= 3
    assert a.asnumpy().max() == 6.0
    a /= 2
    assert a.asnumpy().max() == 3.0


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert (a > b).asnumpy().tolist() == [0.0, 0.0, 1.0]
    assert (a == b).asnumpy().tolist() == [0.0, 1.0, 0.0]
    assert (a <= b).asnumpy().tolist() == [1.0, 1.0, 0.0]


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[0].shape == (3, 4)
    assert a[1, 2].shape == (4,)
    assert a[0, 1, 2].asscalar() == 6
    assert a[:, 1].shape == (2, 4)
    assert a[0, :, 1:3].shape == (3, 2)
    # setitem
    b = nd.zeros((3, 3))
    b[1] = 5.0
    assert b.asnumpy()[1].tolist() == [5.0, 5.0, 5.0]
    b[0, 2] = 1.0
    assert b.asnumpy()[0, 2] == 1.0
    b[:] = 9.0
    assert b.asnumpy().min() == 9.0


def test_reshape_magic_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((0, 0, 2, 2)).shape == (2, 3, 2, 2)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)
    assert a.reshape((2, -4, -1, 3, 4)).shape == (2, 1, 3, 4)


def test_reductions():
    a_np = np.random.uniform(size=(2, 3, 4)).astype(np.float32)
    a = nd.array(a_np)
    assert_almost_equal(a.sum().asnumpy(), a_np.sum())
    assert_almost_equal(a.sum(axis=1).asnumpy(), a_np.sum(axis=1))
    assert_almost_equal(a.mean(axis=(0, 2)).asnumpy(), a_np.mean(axis=(0, 2)))
    assert_almost_equal(a.max(axis=-1, keepdims=True).asnumpy(),
                        a_np.max(axis=-1, keepdims=True))
    assert_almost_equal(nd.sum(a, axis=1, exclude=True).asnumpy(),
                        a_np.sum(axis=(0, 2)))
    assert_almost_equal(a.norm().asnumpy(), np.sqrt((a_np ** 2).sum()),
                        rtol=1e-4)
    assert int(a.argmax().asscalar()) == int(a_np.argmax())


def test_dot():
    a_np = np.random.uniform(size=(3, 4)).astype(np.float32)
    b_np = np.random.uniform(size=(4, 5)).astype(np.float32)
    assert_almost_equal(nd.dot(nd.array(a_np), nd.array(b_np)).asnumpy(),
                        a_np @ b_np, rtol=1e-4, atol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a_np), nd.array(b_np.T), transpose_b=True).asnumpy(),
        a_np @ b_np, rtol=1e-4, atol=1e-4)
    # batch_dot
    x = np.random.uniform(size=(2, 3, 4)).astype(np.float32)
    y = np.random.uniform(size=(2, 4, 5)).astype(np.float32)
    assert_almost_equal(nd.batch_dot(nd.array(x), nd.array(y)).asnumpy(),
                        np.matmul(x, y), rtol=1e-4, atol=1e-4)


def test_shape_ops():
    a = nd.array(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.T.shape == (4, 3, 2)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.expand_dims(0).squeeze(0).shape == (2, 3, 4)
    assert a.flatten().shape == (2, 12)
    assert nd.concat(a, a, dim=1).shape == (2, 6, 4)
    assert nd.stack(a, a, axis=0).shape == (2, 2, 3, 4)
    parts = nd.split(a, num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
    sq = nd.split(a, num_outputs=3, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert nd.tile(nd.ones((2, 2)), (2, 3)).shape == (4, 6)
    assert nd.repeat(nd.ones((2, 2)), 3, axis=0).shape == (6, 2)
    assert a.slice_axis(axis=2, begin=1, end=3).shape == (2, 3, 2)
    s = nd.slice(a, begin=(0, 1), end=(2, 3))
    assert s.shape == (2, 2, 4)


def test_take_pick_gather():
    a = nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    t = nd.take(a, nd.array([0, 2]), axis=0)
    assert t.shape == (2, 4)
    assert t.asnumpy()[1, 0] == 8.0
    p = nd.pick(a, nd.array([1, 0, 3]), axis=1)
    assert p.asnumpy().tolist() == [1.0, 4.0, 11.0]
    g = nd.gather_nd(a, nd.array([[0, 2], [1, 3]]))
    assert g.asnumpy().tolist() == [1.0, 11.0]
    oh = nd.one_hot(nd.array([0, 2]), depth=3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]


def test_ordering():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    v = nd.topk(a, k=2, ret_typ="value")
    assert v.asnumpy().tolist() == [[3.0, 2.0], [5.0, 4.0]]
    idx = nd.topk(a, k=1)
    assert idx.asnumpy().reshape(-1).tolist() == [0.0, 1.0]
    asc = nd.sort(a, is_ascend=True)
    assert asc.asnumpy()[0].tolist() == [1.0, 2.0, 3.0]
    desc = nd.argsort(a, is_ascend=False)
    assert desc.asnumpy()[1].tolist() == [1.0, 2.0, 0.0]


def test_sequence_ops():
    # (T=3, B=2)
    data = nd.array(np.arange(6).reshape(3, 2).astype(np.float32))
    lens = nd.array([2.0, 3.0])
    m = nd.SequenceMask(data, sequence_length=lens, use_sequence_length=True,
                        value=-1.0)
    out = m.asnumpy()
    assert out[2, 0] == -1.0 and out[2, 1] == 5.0
    last = nd.SequenceLast(data, sequence_length=lens, use_sequence_length=True)
    assert last.asnumpy().tolist() == [2.0, 5.0]
    rev = nd.SequenceReverse(data, sequence_length=lens, use_sequence_length=True)
    assert rev.asnumpy()[0].tolist() == [2.0, 5.0]


def test_dtype_cast_copy():
    a = nd.ones((2, 2))
    b = a.astype("int32")
    assert "int32" in str(b.dtype)
    c = a.copy()
    c[:] = 5
    assert a.asnumpy().max() == 1.0
    d = nd.cast(a, dtype="float16")
    assert "float16" in str(d.dtype)


def test_context_placement():
    a = nd.ones((2, 2), ctx=mx.tpu(0))
    assert a.context.device_id == 0
    b = a.as_in_context(mx.tpu(1))
    assert b.context.device_id == 1
    assert_almost_equal(a.asnumpy(), b.asnumpy())
    assert mx.num_devices() >= 8  # virtual host platform in tests


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.params")
    d = {"w": nd.array(np.random.uniform(size=(3, 3)).astype(np.float32)),
         "b": nd.ones((7,), dtype="int32")}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"].asnumpy(), d["w"].asnumpy())
    assert loaded["b"].asnumpy().tolist() == [1] * 7
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    back = nd.load(fname)
    assert isinstance(back, list) and back[1].shape == (3,)


def test_bfloat16_save_load(tmp_path):
    fname = str(tmp_path / "bf16.params")
    a = nd.ones((4, 4)).astype("bfloat16")
    nd.save(fname, {"x": a})
    out = nd.load(fname)["x"]
    assert "bfloat16" in str(out.dtype)
    assert out.astype("float32").asnumpy().max() == 1.0


@with_seed(42)
def test_random_reproducibility():
    mx.random.seed(7)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    assert np.array_equal(a, b)
    c = nd.random.uniform(shape=(5,)).asnumpy()
    assert not np.array_equal(b, c)  # stream advances
    n = nd.random.normal(0.0, 1.0, shape=(10000,))
    assert abs(float(n.mean().asscalar())) < 0.05
    r = nd.random.randint(0, 10, shape=(100,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == 3.5
    with pytest.raises(Exception):
        nd.ones((2, 2)).asscalar()
    assert len(nd.zeros((5, 2))) == 5
    rows = list(nd.array([[1.0], [2.0]]))
    assert rows[1].asscalar() == 2.0


def test_where_clip_misc():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    assert nd.where(cond, x, y).asnumpy().tolist() == [1.0, 20.0, 3.0]
    assert nd.clip(x, 1.5, 2.5).asnumpy().tolist() == [1.5, 2.0, 2.5]
    assert nd.add_n(x, y, x).asnumpy().tolist() == [12.0, 24.0, 36.0]


def test_array_indexer_conventions():
    """Array indexers: float dtypes are POSITIONS (cast to int32, the
    classic take convention); genuinely-boolean masks raise with a
    pointer at nd.boolean_mask (data-dependent shape can't trace)."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.ndarray import NDArray

    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    rows = a[nd.array([0.0, 2.0])]
    np.testing.assert_array_equal(rows.asnumpy(),
                                  [[0, 1, 2, 3], [8, 9, 10, 11]])
    np.testing.assert_array_equal(
        a[nd.array([1], dtype="int32")].asnumpy(), [[4, 5, 6, 7]])
    with pytest.raises(MXNetError, match="boolean_mask"):
        a[NDArray(jnp.asarray([True, False, True]))]
    a[nd.array([0.0])] = 7.0
    assert (a.asnumpy()[0] == 7).all()
