"""Benchmark: BERT pretraining throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The metric is tokens/sec/chip on a fused BERT pretraining step (BASELINE.md
config #3); vs_baseline is achieved MFU divided by the 0.45 north-star MFU.

Resilience contract (BASELINE.md "Measurement protocol" + round-2 postmortem):
the orchestrator retries the accelerator path up to 3 times with backoff on
ANY child failure (transient `UNAVAILABLE` from the TPU tunnel included),
falls back to the CPU smoke configuration, and ALWAYS exits 0 with a JSON
line — carrying an "error" field instead of crashing when everything failed.
The line records which platform actually ran.

Workloads (child mode, selected with --workload):
  bert    — BERT-base pretraining, bf16 + Pallas flash attention + LAMB with
            f32 master weights (the MFU flagship; default)
  resnet  — ResNet-50 ImageNet-shaped data-parallel training step, img/s/chip
            (BASELINE.md config #2), reported in the "extra" field by the
            orchestrator when MXTPU_BENCH_RESNET=1
"""

import json
import os
import subprocess
import sys
import time

TPU_ATTEMPTS = int(os.environ.get("MXTPU_BENCH_ATTEMPTS", "3"))
# first compile through the tunnel can be slow; a DEAD tunnel hangs until
# this timeout, so it bounds worst-case bench wall-clock (tunable)
# successful TPU runs (compile through the tunnel + 13 steps) measured
# ~4-6 min end to end; 900 s gives 2-3x headroom while bounding the cost
# of a hard-down tunnel to ~45 min across the retry ladder
TPU_TIMEOUT = int(os.environ.get("MXTPU_BENCH_TPU_TIMEOUT", "900"))
CPU_TIMEOUT = int(os.environ.get("MXTPU_BENCH_CPU_TIMEOUT", "900"))
BACKOFFS = (10, 30)


# --------------------------------------------------------------------- #
# child: actually run one workload and print its JSON line
# --------------------------------------------------------------------- #

def _peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local chip generation (used for MFU)."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    table = {
        "v4": 275e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v6e": 918e12,
    }
    for k, v in table.items():
        if gen.startswith(k):
            return v
    return 197e12  # default: v5e


def _bert_flops_per_step(B, T, M, L, units, hidden, vocab):
    """Honest fwd+bwd FLOP count (6x matmul rule: 2x fwd, 4x bwd):
    encoder matmuls + O(T^2) attention + MLM/NSP heads. Embedding
    gathers are excluded (they are not matmul FLOPs)."""
    enc = 6.0 * B * T * L * (4 * units * units + 2 * units * hidden)
    attn = 12.0 * L * B * T * T * units
    heads = 6.0 * B * M * units * (vocab + units) + 6.0 * B * (
        units * units + 2 * units)
    return enc + attn + heads


def _run_bert(on_tpu):
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import bert as bert_mod

    size = os.environ.get("MXTPU_BENCH_MODEL", "base")
    if size not in ("base", "large"):
        raise ValueError(f"MXTPU_BENCH_MODEL must be base|large, got {size!r}")
    if on_tpu:
        default_b = "16" if size == "large" else "48"
        B = int(os.environ.get("MXTPU_BENCH_BATCH", default_b))
        T, M = 512, 76
        dtype = "bfloat16"
        steps, warmup = 10, 3
        flash = True
    else:  # CPU smoke mode so the bench is runnable anywhere
        B, T, M = 4, 128, 20
        dtype = "float32"
        steps, warmup = 3, 1
        flash = False
    remat = os.environ.get("MXTPU_BENCH_REMAT", "0") == "1"
    dropout = float(os.environ.get("MXTPU_BENCH_DROPOUT", "0.1"))

    mx.random.seed(0)
    ctor = bert_mod.bert_large if size == "large" else bert_mod.bert_base
    model = ctor(dtype=dtype, max_length=T, flash=flash,
                 remat=remat, dropout=dropout)
    model.initialize()
    pre = bert_mod.BERTForPretraining(model)
    pre.initialize()

    rng = np.random.RandomState(0)
    batch = (
        nd.array(rng.randint(0, 30522, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
        nd.array(np.full((B,), T), dtype="int32"),
        nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
        nd.array(rng.randint(0, 30522, (B, M)), dtype="int32"),
        nd.ones((B, M)),
        nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
    )

    trainer = parallel.SPMDTrainer(
        pre, forward_loss=bert_mod.pretraining_loss, optimizer="lamb",
        optimizer_params={"learning_rate": 1e-4,
                          "multi_precision": dtype != "float32"},
        sharding="replicated")

    for _ in range(warmup):
        loss = trainer.step(*batch)
    float(loss.asnumpy())  # real fence: block_until_ready is a no-op on
    # the axon tunnel backend (verified empirically), so the fetch IS the
    # synchronization point — the reference's asnumpy contract

    trace_dir = os.environ.get("MXTPU_BENCH_TRACE")
    if trace_dir:
        # profiler evidence (BASELINE.md protocol): proves the Pallas
        # kernel executes and shows comm/compute overlap in the step
        import jax.profiler
        with jax.profiler.trace(trace_dir):
            loss = trainer.step(*batch)
            float(loss.asnumpy())

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(*batch)
    float(loss.asnumpy())
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    tokens_per_sec_chip = B * T * steps / dt / n_chips
    flops_per_step = _bert_flops_per_step(
        B, T, M, model.num_layers, model._units, model.hidden_size,
        model.vocab_size)
    mfu = (flops_per_step * steps / dt) / (_peak_flops_per_chip() * n_chips)

    return {
        "metric": f"bert_{size}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "batch": B,
        "seq_len": T,
        "dtype": dtype,
        "flash": flash,
    }


def _run_resnet(on_tpu):
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.gluon import loss as gloss
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    if on_tpu:
        B, side = 64, 224
        dtype = "bfloat16"
        steps, warmup = 10, 3
    else:
        B, side = 8, 64
        dtype = "float32"
        steps, warmup = 2, 1

    mx.random.seed(0)
    net = resnet50_v1()
    net.initialize()
    if dtype != "float32":
        # cast params too (the reference's net.cast('float16') recipe) —
        # a bf16 input against f32 weights silently promotes every conv
        # back to f32; multi_precision SGD keeps f32 master weights
        rng0 = np.random.RandomState(0)
        net(nd.array(rng0.rand(1, 3, side, side).astype("float32")))
        net.cast(dtype)

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(B, 3, side, side).astype("float32"))
    if dtype != "float32":
        x = x.astype(dtype)
    y = nd.array(rng.randint(0, 1000, (B,)), dtype="int32")

    trainer = parallel.SPMDTrainer(
        net, loss=gloss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                          "multi_precision": dtype != "float32"},
        sharding="replicated")

    for _ in range(warmup):
        loss = trainer.step(x, y)
    float(loss.asnumpy())
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(x, y)
    float(loss.asnumpy())
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    return {
        "metric": "resnet50_train_img_per_sec_per_chip",
        "value": round(B * steps / dt / n_chips, 2),
        "unit": "img/s/chip",
        "vs_baseline": 0.0,
        "batch": B,
        "dtype": dtype,
    }


def _child_main(workload):
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    result = {"bert": _run_bert, "resnet": _run_resnet}[workload](on_tpu)
    result["platform"] = jax.devices()[0].platform
    print("BENCH_RESULT " + json.dumps(result))


# --------------------------------------------------------------------- #
# orchestrator: retry accelerator, fall back to CPU, never crash
# --------------------------------------------------------------------- #

def _attempt(workload, platform, timeout):
    """Run one child attempt; returns (result dict | None, error string)."""
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run",
             "--workload", workload],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            try:
                return json.loads(line[len("BENCH_RESULT "):]), ""
            except json.JSONDecodeError as e:
                return None, f"unparseable result line: {e}"
    tail = (r.stderr or r.stdout or "").strip().splitlines()[-8:]
    return None, f"rc={r.returncode}: " + " | ".join(tail)


def _measure(workload):
    """TPU with retries, then CPU fallback. Returns (result|None, errors)."""
    errors = []
    cpu_res = None
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        for i in range(TPU_ATTEMPTS):
            res, err = _attempt(workload, None, TPU_TIMEOUT)
            if res is not None and res.get("platform") != "cpu":
                res["attempts"] = i + 1
                return res, errors
            if res is not None:
                # no accelerator on this machine: the child already ran the
                # full CPU smoke — keep it as the fallback, don't re-run
                cpu_res = res
                errors.append(f"attempt {i + 1} landed on cpu")
                break
            errors.append(err)
            if i < TPU_ATTEMPTS - 1:
                time.sleep(BACKOFFS[min(i, len(BACKOFFS) - 1)])
    if cpu_res is None:
        cpu_res, err = _attempt(workload, "cpu", CPU_TIMEOUT)
        if cpu_res is None:
            errors.append(err)
            return None, errors
    cpu_res["attempts"] = len(errors) + 1
    return cpu_res, errors


def main():
    if "--run" in sys.argv:
        wl = "bert"
        if "--workload" in sys.argv:
            wl = sys.argv[sys.argv.index("--workload") + 1]
        _child_main(wl)
        return

    result, errors = _measure("bert")
    if result is None:
        size = os.environ.get("MXTPU_BENCH_MODEL", "base")
        result = {
            "metric": f"bert_{size}_pretrain_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "platform": "none",
        }
    if errors:
        # transient/retry history; "error" (the hard-failure marker) is
        # reserved for the zero-value placeholder above
        key = "error" if result.get("platform") == "none" else "retries"
        result[key] = "; ".join(e for e in errors if e)[:500]

    if os.environ.get("MXTPU_BENCH_RESNET") == "1":
        rn, rn_errors = _measure("resnet")
        if rn is not None:
            result["extra"] = rn
        elif rn_errors:
            result["extra"] = {"error": "; ".join(rn_errors)[:300]}

    print(json.dumps(result))


if __name__ == "__main__":
    main()
