"""Shared per-layer rematerialization helper.

``jax.checkpoint`` around one block call (the reference's
mirroring/memonger memory plan, SURVEY.md §2.1 PlanMemory row). The
block's dropout keys are drawn OUTSIDE the checkpoint and passed as an
explicit input: provider state mutated inside the checkpoint trace would
leak inner tracers, and an input key replays identically in the remat
pass. Params enter via closure → saved as residuals, not recomputed."""

from __future__ import annotations

import jax

from .. import random as _rand
from ..ndarray import NDArray

__all__ = ["remat_call"]


def remat_call(block, *args):
    """Apply ``block(*args)`` under jax.checkpoint. ``args`` are NDArrays
    or None; returns an NDArray."""
    base = _rand.new_key()
    vals = [a._data if a is not None else None for a in args]

    def _ckpt(key, *vs):
        with _rand.key_provider(key):
            nds = [NDArray(v) if v is not None else None for v in vs]
            return block(*nds)._data

    return NDArray(jax.checkpoint(_ckpt)(base, *vals))
