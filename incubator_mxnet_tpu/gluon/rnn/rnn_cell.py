"""Recurrent cell API (re-design of `python/mxnet/gluon/rnn/rnn_cell.py` —
file-level citation, SURVEY.md caveat).

Cells are single-step HybridBlocks: ``cell(input_t, states) ->
(output_t, new_states)``. ``unroll`` expands a fixed length at trace time
(a static Python loop — each step is the same traced cell, XLA fuses the
chain); the fused ``rnn.LSTM``/``GRU``/``RNN`` layers (rnn_layer.py) are
the ``lax.scan`` path and should be preferred for long sequences.
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "ZoneoutCell", "HybridSequentialRNNCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell",
           "ResidualCell", "DropoutCell", "ModifierCell"]


class RecurrentCell(HybridBlock):
    """Base class (parity: gluon.rnn.RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset step counters before a new unroll."""
        self._init_counter = -1
        self._counter = -1
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (zeros by default), one per ``state_info`` entry."""
        from ... import ndarray as nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = list(info["shape"])
            if shape[0] == 0:
                shape[0] = batch_size
            states.append(func(shape=tuple(shape), **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell ``length`` steps (parity: RecurrentCell.unroll).

        inputs: one array in ``layout`` or a length-``length`` list of
        (B, C) steps. Returns (outputs, states).
        """
        from ... import ndarray as nd
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            batch = inputs.shape[layout.find("N")]
            steps = [nd.squeeze(s, axis=axis)
                     for s in nd.split(inputs, num_outputs=length, axis=axis)]
        else:
            steps = list(inputs)
            batch = steps[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch,
                                           dtype=steps[0].dtype)
        states = begin_state
        outputs = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=axis)
            masked = nd.SequenceMask(stacked, sequence_length=valid_length,
                                     use_sequence_length=True,
                                     axis=axis)
            if merge_outputs is False:
                outputs = [nd.squeeze(s, axis=axis) for s in
                           nd.split(masked, num_outputs=length, axis=axis)]
            else:
                outputs = masked
        elif merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


HybridRecurrentCell = RecurrentCell  # the reference distinguishes; we don't


class _BaseGatedCell(RecurrentCell):
    """Shared param plumbing for RNN/LSTM/GRU cells."""

    _gates = 1

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        G = self._gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(G * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(G * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(G * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(G * hidden_size,),
                init=h2h_bias_initializer)

    def infer_shape(self, inputs, *args):
        self.i2h_weight.shape = (self._gates * self._hidden_size,
                                 inputs.shape[-1])

    @property
    def hidden_size(self):
        return self._hidden_size


class RNNCell(_BaseGatedCell):
    """Elman cell: h' = act(W_x x + b_x + W_h h + b_h)
    (reference: rnn_cell.py RNNCell)."""

    _gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        h = states[0]
        pre = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size) + \
            F.FullyConnected(h, h2h_weight, h2h_bias,
                             num_hidden=self._hidden_size)
        out = F.Activation(pre, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseGatedCell):
    """LSTM cell, gate order ``i, f, g, o`` (reference: rnn_cell.py
    LSTMCell; same order as the fused op — ops/rnn.py)."""

    _gates = 4

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        h, c = states
        G = 4 * self._hidden_size
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=G) \
            + F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=G)
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * F.tanh(c2)
        return h2, [h2, c2]


class GRUCell(_BaseGatedCell):
    """GRU cell, gate order ``r, z, n`` (reference: rnn_cell.py GRUCell)."""

    _gates = 3

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        h = states[0]
        G = 3 * self._hidden_size
        gx = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=G)
        gh = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=G)
        xr, xz, xn = F.split(gx, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(gh, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        h2 = (1.0 - z) * n + z * h
        return h2, [h2]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in sequence each step
    (parity: SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs))
        return states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, sub = cell(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(sub)
        return inputs, next_states


class ModifierCell(RecurrentCell):
    """Wraps a cell, reusing its parameters (parity: ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.base_cell.begin_state(batch_size=batch_size, func=func,
                                          **kwargs)


class ResidualCell(ModifierCell):
    """output = cell(input) + input (parity: ResidualCell)."""

    def forward(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization: each step keeps the PREVIOUS state with
    probability ``zoneout_states`` (and the previous output with
    ``zoneout_outputs``) instead of the new one (parity: ZoneoutCell;
    Krueger et al. 2017). Training-mode gated like Dropout; at
    inference the cell is a passthrough (the reference's
    Dropout-generated mask becomes all-ones)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import autograd as _ag
        from ... import ndarray as _nd

        out, next_states = self.base_cell(inputs, states)

        def _mix(p, new, old):
            # train/predict-mode gating matches Dropout (is_training,
            # not is_recording); inference is an identity passthrough
            if p == 0.0 or old is None or not _ag.is_training():
                return new
            mask = _nd.random.uniform(0.0, 1.0, shape=new.shape) < p
            return _nd.where(mask, old, new)

        prev_out = self._prev_output
        if prev_out is None:
            prev_out = _nd.zeros_like(out)
        out = _mix(self._zo, out, prev_out)
        next_states = [_mix(self._zs, ns, s)
                       for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class DropoutCell(RecurrentCell):
    """Applies dropout to the input each step (parity: DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class BidirectionalCell(RecurrentCell):
    """Runs two cells over the sequence in opposite directions; only
    usable via ``unroll`` (parity: BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix=None, params=None)
        self.l_cell = l_cell
        self.r_cell = r_cell
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.l_cell.begin_state(batch_size=batch_size, func=func,
                                       **kwargs) + \
            self.r_cell.begin_state(batch_size=batch_size, func=func,
                                    **kwargs)

    def forward(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            batch = inputs.shape[layout.find("N")]
            steps = [nd.squeeze(s, axis=axis)
                     for s in nd.split(inputs, num_outputs=length, axis=axis)]
        else:
            steps = list(inputs)
            batch = steps[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch,
                                           dtype=steps[0].dtype)
        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, steps, begin_state[:nl], layout="NTC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            rev_steps = list(reversed(steps))
        else:
            # length-aware reversal so the backward cell sees each
            # sequence's valid frames first, not its padding (reference:
            # SequenceReverse with use_sequence_length)
            stacked = nd.stack(*steps, axis=0)  # (T,B,C)
            rev = nd.SequenceReverse(stacked, sequence_length=valid_length,
                                     use_sequence_length=True)
            rev_steps = [nd.squeeze(s, axis=0) for s in
                         nd.split(rev, num_outputs=length, axis=0)]
        r_out, r_states = self.r_cell.unroll(
            length, rev_steps, begin_state[nl:], layout="NTC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_out = list(reversed(r_out))
        else:
            rev = nd.SequenceReverse(nd.stack(*r_out, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True)
            r_out = [nd.squeeze(s, axis=0) for s in
                     nd.split(rev, num_outputs=length, axis=0)]
        outputs = [nd.concat(lo, ro, dim=-1)
                   for lo, ro in zip(l_out, r_out)]
        if merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states


# the reference distinguishes hybrid/non-hybrid sequential containers;
# one implementation serves both here (everything traces under jit)
HybridSequentialRNNCell = SequentialRNNCell
