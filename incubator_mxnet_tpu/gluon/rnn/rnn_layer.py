"""Fused RNN layers (re-design of `python/mxnet/gluon/rnn/rnn_layer.py` —
file-level citation, SURVEY.md caveat).

Each layer owns per-(layer, direction) parameters and concatenates them
into the flat vector the fused ``RNN`` op consumes (the reference does the
same before calling its cuDNN-backed op); the recurrence itself is a
``lax.scan`` on the MXU — see ops/rnn.py.
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; expected TNC or NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]
        G, H = self._gates, hidden_size
        self._param_names = []
        with self.name_scope():
            for layer in range(num_layers):
                in_sz = input_size if layer == 0 else H * self._dir
                for d in range(self._dir):
                    tag = f"{'lr'[d]}{layer}"
                    names = [f"{tag}_i2h_weight", f"{tag}_h2h_weight",
                             f"{tag}_i2h_bias", f"{tag}_h2h_bias"]
                    shapes = [(G * H, in_sz), (G * H, H), (G * H,), (G * H,)]
                    inits = [i2h_weight_initializer, h2h_weight_initializer,
                             i2h_bias_initializer, h2h_bias_initializer]
                    for n, s, i in zip(names, shapes, inits):
                        p = self.params.get(n, shape=s, init=i,
                                            allow_deferred_init=True)
                        setattr(self, n, p)
                    self._param_names.append(names)

    def infer_shape(self, x, *args):
        in_sz = x.shape[2]  # channel axis is last in both TNC and NTC
        G, H = self._gates, self._hidden_size
        for idx, names in enumerate(self._param_names):
            layer = idx // self._dir
            layer_in = in_sz if layer == 0 else H * self._dir
            getattr(self, names[0]).shape = (G * H, layer_in)

    def state_info(self, batch_size=0):
        infos = [{"shape": (self._num_layers * self._dir, batch_size,
                            self._hidden_size), "__layout__": "LNC"}]
        if self._mode == "lstm":
            infos.append(dict(infos[0]))
        return infos

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        func = func or nd.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch, dtype=inputs.dtype,
                                      ctx=getattr(inputs, "context", None))
        if not isinstance(states, (list, tuple)):
            states = [states]

        # pack: all weights (layer-major, direction-minor), then all biases
        # — the exact layout ops/rnn.py documents
        flat = []
        for names in self._param_names:
            flat.append(F.reshape(params[names[0]], shape=(-1,)))
            flat.append(F.reshape(params[names[1]], shape=(-1,)))
        for names in self._param_names:
            flat.append(params[names[2]])
            flat.append(params[names[3]])
        packed = F.concat(*flat, dim=0) if len(flat) > 1 else flat[0]

        out = F.RNN(inputs, packed, *states, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        outputs, states_out = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, states_out

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout!r}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (parity: gluon.rnn.RNN;
    reference fused op src/operator/rnn.cc)."""

    def __init__(self, hidden_size, num_layers=1, activation="tanh",
                 layout="TNC", **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(mode, hidden_size, num_layers, layout, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM (parity: gluon.rnn.LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU (parity: gluon.rnn.GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, **kwargs)
