"""Deterministic fault injection (chaos) for the serving engine.

Production TPU serving dies from the faults nobody unit-tested: a
checkpoint with a NaN in it warm-started into a live fleet, a DMA that
corrupted one KV page, an allocator squeezed to starvation by a noisy
neighbour, a host stall that blows every deadline, a preemption SIGTERM
mid-decode. This module makes those faults INJECTABLE, SEEDED and
REPRODUCIBLE, so `tools/chaos_bench.py` (ci/run.sh ``chaossmoke``
stage) can assert the resilience contract instead of hoping:

  - every request ends in a structured terminal ``Outcome``;
  - unfaulted requests emit BIT-IDENTICAL tokens to a fault-free run
    (no cross-slot contamination — slots are isolated by construction);
  - ``audit_pages()`` passes after EVERY scheduler step, faults
    included (pages reclaimed exactly, never leaked or double-granted);
  - the decode step still compiles exactly once (the guard flag and
    all fault handling are pure data / host-side bookkeeping).

Injectors hook the scheduler through ``InferenceEngine.run``'s
``before_step`` callback — they fire at a given scheduler ITERATION
(not wall time), so a batch-submitted workload replays the same fault
at the same point every run. All randomness comes from the injector's
own seeded ``RandomState``.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .engine import InferenceEngine, Request
from .events import EventType
from .outcomes import Outcome
from .router import ReplicaState, Router

__all__ = ["ChaosInjector", "NaNWeights", "CorruptPageWrite",
           "CorruptPageScale", "CorruptDemotedPage", "DiskFullDemotion",
           "PagePressure", "DelayedSteps", "CancelStorm", "run_chaos",
           "assert_all_terminal", "assert_health_consistent",
           "FleetInjector", "KillReplica", "SlowReplica",
           "FlappingReplica", "FleetCancelStorm", "MigrateFault",
           "ScaleDownRace", "DrainKill", "SupervisorChaos",
           "run_fleet_chaos", "assert_fleet_health_consistent"]


class ChaosInjector:
    """Base: a seeded fault with an injection log and an ``affected``
    set — the requests whose OUTPUT the fault may legitimately change.
    Everything outside ``affected`` must stay bit-identical to a
    fault-free run (the cross-contamination invariant)."""

    name = "chaos"

    def __init__(self, seed: int = 0):
        self.rng = np.random.RandomState(seed)
        self.log: List[str] = []
        self.affected: List[Request] = []
        self.fired = False

    def _mark(self, *requests: Request):
        for r in requests:
            # identity, not ==: Request is a dataclass whose generated
            # __eq__ compares ndarray fields elementwise
            if not any(r is a for a in self.affected):
                self.affected.append(r)

    def on_step(self, engine: InferenceEngine, step_idx: int) -> None:
        raise NotImplementedError


class NaNWeights(ChaosInjector):
    """Poison the serving weights at step ``at_step`` — the
    'warm-started a bad checkpoint' fault. ``n_entries`` random entries
    of the EMBEDDING table get NaN: the tied LM head multiplies every
    slot's hidden state by that table, so any poisoned entry makes some
    logit non-finite for EVERY live slot — the guard must quarantine
    them all (FAILED_NONFINITE), and every request admitted while the
    poison stands must fail at its prefill guard. The swap goes through
    ``warm_start`` — pure data, decode compile count must stay 1."""

    name = "nan_weights"

    def __init__(self, at_step: int, n_entries: int = 4, seed: int = 0):
        super().__init__(seed)
        self.at_step = at_step
        self.n_entries = n_entries

    def on_step(self, engine, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        self.fired = True
        params = {str(i): np.asarray(p.data().asnumpy())
                  for i, p in enumerate(engine._eng_params)}
        # the embedding/tied-head table is params["0"] by construction
        # order (word_embed first); fall back to the largest 2-D tensor
        emb_key = "0"
        if params[emb_key].ndim != 2:
            emb_key = max((k for k, v in params.items() if v.ndim == 2),
                          key=lambda k: params[k].size)
        tab = params[emb_key].copy()
        flat = tab.reshape(-1)
        idx = self.rng.choice(flat.size, size=min(self.n_entries,
                                                  flat.size),
                              replace=False)
        flat[idx] = np.nan
        params[emb_key] = tab
        engine.warm_start(params=params)
        # every request not already terminal is poisoned from here on
        for slot in engine._slots:
            if slot is not None:
                self._mark(slot.request)
        self._mark(*engine._queue)
        self.log.append(f"step {step_idx}: NaN-poisoned {len(idx)} "
                        f"entries of param[{emb_key}] via warm_start")

    def mark_submitted_after(self, request: Request):
        """Requests submitted after the poison fired are affected too —
        the harness calls this from its submit wrapper."""
        if self.fired:
            self._mark(request)


class CorruptPageWrite(ChaosInjector):
    """Corrupt one LIVE, PRIVATE (refcount-1) mapped KV page of a
    decoding slot at step ``at_step`` — the 'DMA wrote garbage /
    dropped the write' fault, at page granularity across every layer's
    K and V pool.

    ``mode='nan'``: the slot's attention output goes non-finite the
    next decode step — the guard must quarantine exactly that slot.
    ``mode='zero'``: a dropped write — finite garbage the guard CANNOT
    see; the slot's request is marked affected (its tokens may
    legitimately change) and the invariant asserted is that NO OTHER
    request changes (cross-slot isolation) and all accounting stays
    exact. Defers to the next step when no candidate slot is live."""

    name = "corrupt_page"

    def __init__(self, at_step: int, mode: str = "nan", seed: int = 0):
        super().__init__(seed)
        if mode not in ("nan", "zero"):
            raise MXNetError(f"corrupt mode {mode!r} not in nan|zero")
        self.at_step = at_step
        self.mode = mode
        self.page: Optional[int] = None

    def on_step(self, engine, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        if getattr(engine, "_kv_spec", None) is not None and \
                self.mode == "nan":
            raise MXNetError(
                "CorruptPageWrite(mode='nan') cannot express NaN in an "
                "int8/fp8 page payload — on a quantized engine the "
                "non-finite channel is the per-page SCALE: use "
                "CorruptPageScale")
        ps = engine.page_size
        cands = []
        for s in range(engine.num_slots):
            slot = engine._slots[s]
            if slot is None or slot.prefilling:
                continue
            n_read = -(-int(engine._lengths[s]) // ps)
            for p in slot.row[:n_read]:
                p = int(p)
                if p and engine._alloc.refcount(p) == 1:
                    cands.append((s, p))
        if not cands:
            return                       # defer until a slot is live
        self.fired = True
        s, page = cands[self.rng.randint(len(cands))]
        val = np.nan if self.mode == "nan" else 0.0
        newk, newv = [], []
        for kp, vp in zip(engine._kpools, engine._vpools):
            k = np.asarray(kp).copy()
            v = np.asarray(vp).copy()
            k[page] = val
            v[page] = val
            newk.append(jnp.asarray(k))
            newv.append(jnp.asarray(v))
        engine._kpools = tuple(newk)
        engine._vpools = tuple(newv)
        self.page = page
        self._mark(engine._slots[s].request)
        self.log.append(f"step {step_idx}: {self.mode}-corrupted page "
                        f"{page} (slot {s}, refcount 1) in all layers")


class CorruptPageScale(ChaosInjector):
    """Corrupt the per-page SCALE metadata of a live quantized KV page
    — the quantized pool's own corruption channel: int8/fp8 payloads
    cannot carry NaN, so a torn scale (bit-flipped SMEM word, stale
    metadata after a botched migration) is how a quantized cache
    poisons reads. Requires a quantized engine (``kv_quant`` set);
    refuses otherwise.

    By default the target is a live SHARED page (refcount >= 2 — a
    prefix page mapped by a slot AND retained by the index or a
    sibling slot): the sharpest case, because the scale is shared
    exactly like the page, so one torn word poisons every reader, and
    quarantine must both fail the readers AND flush the index so no
    FUTURE admission maps the poisoned page (the freed page's scale is
    reset on reallocation). ``shared=False`` targets a private
    (refcount-1) page — the blast radius is provably one slot.

    ``mode='nan'`` / ``'inf'``: the dequantized K/V go non-finite and
    the next decode step's sign-encoded guard must quarantine exactly
    the slots mapping the page (FAILED_NONFINITE, nothing from the
    poisoned step recorded). ``mode='zero'`` zeroes the page's amax —
    the scale collapses to the zero-range convention (1.0) and the
    page dequantizes its raw codes at the wrong magnitude: finite
    garbage the guard CANNOT see, the metadata twin of a dropped
    write; affected slots may emit anything, everyone else must stay
    bit-identical. Defers to a later step when no candidate page is
    live."""

    name = "corrupt_page_scale"

    _VALS = {"nan": np.nan, "inf": np.inf, "zero": 0.0}

    def __init__(self, at_step: int, mode: str = "nan",
                 shared: bool = True, seed: int = 0):
        super().__init__(seed)
        if mode not in self._VALS:
            raise MXNetError(f"scale-corrupt mode {mode!r} not in "
                             f"nan|inf|zero")
        self.at_step = at_step
        self.mode = mode
        self.shared = shared
        self.page: Optional[int] = None

    def on_step(self, engine, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        if engine._kv_spec is None:
            raise MXNetError("CorruptPageScale needs a quantized "
                             "engine (kv_quant='int8'/'fp8_e4m3') — "
                             "unquantized pools have no scale metadata")
        ps = engine.page_size
        want_shared = self.shared
        cands = []
        for s in range(engine.num_slots):
            slot = engine._slots[s]
            if slot is None or slot.prefilling:
                continue
            n_read = -(-int(engine._lengths[s]) // ps)
            for p in slot.row[:n_read]:
                p = int(p)
                if not p:
                    continue
                rc = engine._alloc.refcount(p)
                if (rc >= 2) == want_shared:
                    cands.append(p)
        if not cands:
            return                       # defer until a candidate lives
        self.fired = True
        page = cands[self.rng.randint(len(cands))]
        val = self._VALS[self.mode]
        for a in engine._kamax:          # host-owned page metadata —
            a[page] = val                # every layer's K and V scale
        for a in engine._vamax:
            a[page] = val
        self.page = page
        hit = []
        for s in range(engine.num_slots):
            slot = engine._slots[s]
            if slot is not None and any(int(p) == page
                                        for p in slot.row):
                hit.append(s)
                self._mark(slot.request)
        if self.mode == "zero":
            # finite corruption survives quarantine-free: a poisoned
            # SHARED page stays in the prefix index, so any later
            # admission may map it — every not-yet-finished request is
            # in the blast radius (the nan/inf modes need no such
            # blanket: quarantine flushes the index the same step)
            for slot in engine._slots:
                if slot is not None:
                    self._mark(slot.request)
            self._mark(*engine._queue)
        self.log.append(
            f"step {step_idx}: {self.mode}-corrupted the scale of "
            f"page {page} (refcount "
            f"{engine._alloc.refcount(page)}, slots {hit}) in all "
            f"layers, K and V")

    def mark_submitted_after(self, request: Request):
        """Zero-mode only: requests submitted after the fault may map
        the still-cached poisoned page (no quarantine ever flushes
        it). ``run_chaos`` submits everything up front — the fire-time
        blanket mark covers batch scenarios — so only a harness that
        feeds ``arrival_times`` (late submissions) needs to route its
        submits through this (same contract as
        ``NaNWeights.mark_submitted_after``)."""
        if self.fired and self.mode == "zero":
            self._mark(request)


class CorruptDemotedPage(ChaosInjector):
    """Corrupt one DEMOTED prefix page's at-rest payload — the 'bit rot
    below HBM' fault for the hierarchical cache (docs/SERVING.md
    "Hierarchical prefix cache"): a flipped byte in the host-DRAM pool,
    or in a disk-tier shard file, of a page the engine believes it can
    re-admit by copy.

    The integrity contract makes ``affected`` EMPTY: every DRAM entry
    carries a crc32 verified at promotion (the disk tier rides the
    checkpoint manifest's per-shard crc plus the same payload crc), so
    the corrupted page must be caught, dropped, and counted
    (``tier_crc_fallbacks``), and the admission must fall back to
    recomputing prefill — producing BIT-IDENTICAL tokens to a
    fault-free run. A fallback that records even one garbage token is
    the invariant breach this injector exists to catch.

    ``tier`` targets "dram", "disk", or None (whichever has an entry
    first, DRAM preferred). Defers until the engine's tier store holds
    a candidate. Requires a tiered engine (``kv_tiers`` set)."""

    name = "corrupt_demoted_page"

    def __init__(self, at_step: int, tier: Optional[str] = None,
                 seed: int = 0):
        super().__init__(seed)
        if tier not in (None, "dram", "disk"):
            raise MXNetError(f"demoted-corrupt tier {tier!r} not in "
                             f"dram|disk|None")
        self.at_step = at_step
        self.tier = tier

    def on_step(self, engine, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        if engine._tiers is None:
            raise MXNetError("CorruptDemotedPage needs a tiered engine "
                             "(kv_tiers set) — there is nothing "
                             "demoted to corrupt otherwise")
        cands = [(k, e) for k, e in engine._tiers.entries()
                 if self.tier is None or e.tier == self.tier]
        if not cands:
            return                       # defer until something demoted
        if self.tier is None:
            dram = [c for c in cands if c[1].tier == "dram"]
            cands = dram or cands
        key, ent = cands[self.rng.randint(len(cands))]
        if ent.tier == "dram":
            # flip one byte of the layer-0 K payload (payloads may be
            # read-only views of device buffers — corrupt a copy and
            # swap it in; the stored crc now convicts it)
            arr = np.array(ent.k_payload[0])
            buf = arr.view(np.uint8).reshape(-1)
            buf[self.rng.randint(buf.size)] ^= 0xFF
            ent.k_payload = (arr,) + tuple(ent.k_payload[1:])
            where = "dram payload"
        else:
            from ..checkpoint.manifest import step_dir
            d = step_dir(engine._tiers.disk_dir, ent.step)
            shards = sorted(f for f in os.listdir(d)
                            if f.endswith(".bin"))
            path = os.path.join(d, shards[0])
            size = os.path.getsize(path)
            off = int(self.rng.randint(size))
            with open(path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]))
            where = f"disk shard {shards[0]}"
        self.fired = True
        self.log.append(f"step {step_idx}: flipped a byte in the "
                        f"{where} of demoted page depth {ent.depth} "
                        f"(key {key.hex()[:16]})")


class DiskFullDemotion(ChaosInjector):
    """Fail the disk tier's writes from step ``at_step`` on — the
    'disk filled up mid-demotion' fault. Wraps the tier store's
    ``_write_step`` seam with an ENOSPC raiser (``mode="torn"`` first
    leaves a partial ``.tmp`` step directory behind, the torn-write
    flavour — a later successful write must clear it, and the startup
    wipe must survive it).

    ``affected`` is EMPTY: a failed demotion degrades to plain
    eviction, loudly (``tier_disk_errors`` counts, the entry is
    dropped, the event lane records the failure) — every request must
    still end in a terminal outcome with tokens bit-identical to a
    fault-free run, because eviction-instead-of-demotion only costs
    recompute, never correctness."""

    name = "disk_full_demotion"

    def __init__(self, at_step: int, mode: str = "enospc",
                 seed: int = 0):
        super().__init__(seed)
        if mode not in ("enospc", "torn"):
            raise MXNetError(f"disk-full mode {mode!r} not in "
                             f"enospc|torn")
        self.at_step = at_step
        self.mode = mode
        self.failed_writes = 0

    def on_step(self, engine, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        if engine._tiers is None or engine._tiers.disk_dir is None:
            raise MXNetError("DiskFullDemotion needs a tiered engine "
                             "with a disk_dir")
        self.fired = True
        store = engine._tiers
        inj = self

        def _enospc(root, step, entries, **kw):
            if inj.mode == "torn":
                from ..checkpoint.manifest import step_dir
                tmp = step_dir(root, step) + ".tmp"
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(tmp, "shards_p0.bin"),
                          "wb") as f:
                    f.write(b"torn")
            inj.failed_writes += 1
            raise OSError(28, "No space left on device (chaos)")

        store._write_step = _enospc
        self.log.append(f"step {step_idx}: disk tier writes now fail "
                        f"ENOSPC ({self.mode})")


class PagePressure(ChaosInjector):
    """Squeeze the allocator: at ``hold_at`` take ``n`` pages (default
    ALL free pages — full starvation) out of circulation through the
    allocator's own ``hold`` bookkeeping, and release them after
    ``release_after`` scheduler steps (None = never). Pure scheduling
    pressure — no request's DATA is touched, so every request that
    completes must still be bit-identical to the fault-free run; the
    rest must end DEADLINE_EXPIRED / FAILED_UNSERVABLE (watchdog or
    stall), never wedge."""

    name = "page_pressure"

    def __init__(self, hold_at: int, release_after: Optional[int] = None,
                 n: Optional[int] = None, seed: int = 0):
        super().__init__(seed)
        self.hold_at = hold_at
        self.release_after = release_after
        self.n = n
        self.held: List[int] = []

    def on_step(self, engine, step_idx):
        if not self.fired and step_idx >= self.hold_at:
            self.fired = True
            self.held = engine._alloc.hold(
                self.n if self.n is not None else engine._alloc.free_count)
            self.log.append(f"step {step_idx}: held {len(self.held)} "
                            f"pages (free now {engine._alloc.free_count})")
        elif (self.held and self.release_after is not None
              and step_idx >= self.hold_at + self.release_after):
            engine._alloc.release_held(self.held)
            self.log.append(f"step {step_idx}: released "
                            f"{len(self.held)} held pages")
            self.held = []


class DelayedSteps(ChaosInjector):
    """Host stall: sleep ``sleep_s`` before every scheduler step in
    [``start``, ``end``) — models a preempted host / GC storm / slow
    interconnect. Drives deadline expiry deterministically when
    ``sleep_s`` dwarfs the requests' ``deadline_s``."""

    name = "delayed_steps"

    def __init__(self, start: int, end: int, sleep_s: float,
                 seed: int = 0):
        super().__init__(seed)
        self.start = start
        self.end = end
        self.sleep_s = sleep_s
        self.stalled_steps = 0

    def on_step(self, engine, step_idx):
        if self.start <= step_idx < self.end:
            self.fired = True
            self.stalled_steps += 1
            time.sleep(self.sleep_s)


class CancelStorm(ChaosInjector):
    """The disconnect fault: clients walk away mid-stream. Every
    ``every`` scheduler steps from ``start``, cancel up to ``n_per``
    seeded-random LIVE requests (queued or slotted — so cancels land
    while queued, mid-prefill, mid-decode and mid-spec-verify as the
    workload moves through those states), up to ``max_cancels`` total
    so part of the workload survives to assert isolation against.
    Cancelled requests are ``affected`` (their streams truncate);
    everything else must stay bit-identical, pages audited after every
    step, and every cancel must land as EXACTLY ONE ``CANCELLED``
    terminal — never a double-finish against a racing completion
    (``engine.cancel`` refuses already-terminal targets)."""

    name = "cancel_storm"

    def __init__(self, start: int, every: int = 2, n_per: int = 1,
                 max_cancels: int = 4, seed: int = 0):
        super().__init__(seed)
        self.start = start
        self.every = max(1, int(every))
        self.n_per = int(n_per)
        self.max_cancels = int(max_cancels)
        self.cancelled: List[Request] = []

    def _live(self, engine) -> List[Request]:
        live = [s.request for s in engine._slots if s is not None]
        live.extend(engine._queue)
        return [r for r in live if r.outcome is None]

    def on_step(self, engine, step_idx):
        if step_idx < self.start or \
                (step_idx - self.start) % self.every or \
                len(self.cancelled) >= self.max_cancels:
            return
        live = self._live(engine)
        if not live:
            return
        n = min(self.n_per, self.max_cancels - len(self.cancelled),
                len(live))
        for i in self.rng.choice(len(live), size=n, replace=False):
            req = live[int(i)]
            if engine.cancel(req, detail=f"{self.name} at step "
                                         f"{step_idx}"):
                self.fired = True
                self.cancelled.append(req)
                self._mark(req)
                self.log.append(f"step {step_idx}: cancelled request "
                                f"{req.request_id} "
                                f"({len(req.token_ids)} tokens in)")


# --------------------------------------------------------------------- #
# fleet-scope injectors (serve/router.py)
# --------------------------------------------------------------------- #

class FleetInjector(ChaosInjector):
    """Base for ROUTER-level injectors: ``on_step(router, step_idx)``
    fires through ``Router.run``'s ``before_step`` hook. Same seeding
    and logging contract as the engine-level injectors."""

    name = "fleet_chaos"

    def on_step(self, router: Router, step_idx: int) -> None:
        raise NotImplementedError


class KillReplica(FleetInjector):
    """Kill one replica — the 'host disappeared' fault. From the fire
    point on, every step of that replica raises ``ReplicaKilled``; the
    router must mark it DEAD and RE-QUEUE its in-flight requests with
    their emitted tokens preserved (resume-from-suffix replay), so
    with requeue budget left NO request is lost and — greedy decode
    being deterministic under position-keyed sampling — every replayed
    request still ends bit-identical to a fault-free run.

    ``phase`` targets the kill: ``"decode"`` defers until the replica
    has a decoding slot with at least one emitted token (a mid-stream
    kill — the replay must preserve a non-empty prefix), ``"prefill"``
    until it has a slot mid-prompt (chunked prefill spreads prompts
    over steps), ``"verify"`` until a speculative verify step has run
    with a decoding slot live (the kill lands inside the
    draft-then-verify window), None fires at ``at_step``
    unconditionally. ``inflight_at_kill`` snapshots (client request,
    copy of its tokens so far) at the fire point — the
    emitted-prefix-preservation oracle for tests."""

    name = "kill_replica"

    def __init__(self, replica: int, at_step: int, phase=None, seed=0):
        super().__init__(seed)
        if phase not in (None, "decode", "prefill", "verify"):
            raise MXNetError(f"kill phase {phase!r} not in "
                             f"decode|prefill|verify|None")
        self.replica = replica
        self.at_step = at_step
        self.phase = phase
        self.inflight_at_kill: List = []

    def _phase_ready(self, router: Router) -> bool:
        eng = router.replicas[self.replica].engine
        if self.phase is None:
            return True
        slots = [s for s in eng._slots if s is not None]
        if self.phase == "prefill":
            return any(s.prefilling for s in slots)
        decoding = [s for s in slots if not s.prefilling
                    and s.request.token_ids]
        if self.phase == "decode":
            return bool(decoding)
        return bool(decoding) and eng.spec_steps > 0   # "verify"

    def on_step(self, router, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        rep = router.replicas[self.replica]
        if rep.state is ReplicaState.DEAD or rep.killed is not None:
            self.fired = True
            return
        if not self._phase_ready(router):
            return                           # defer to a later step
        self.fired = True
        for t in router._inflight:
            if t.replica == self.replica:
                self.inflight_at_kill.append(
                    (t.client, list(t.client.token_ids) +
                     list(t.attempt.token_ids)))
        rep.kill(f"chaos kill ({self.phase or 'any'} phase) at router "
                 f"step {step_idx}")
        self.log.append(
            f"step {step_idx}: killed replica {self.replica} with "
            f"{len(self.inflight_at_kill)} requests in flight")


class SlowReplica(FleetInjector):
    """Stall one replica's steps by ``sleep_s`` for router steps in
    [``start``, ``end``) — the 'neighbour is thrashing / link is slow'
    fault. With ``sleep_s`` over the router's ``heartbeat_timeout_s``,
    ``breaker_failures`` stalled steps must OPEN the breaker
    (DEGRADED: no new admissions, half-open probes on seeded-jitter
    backoff); once the window passes, probes must close it back to
    SERVING and its in-flight requests finish on-replica — slowness
    alone must never lose, re-route, or corrupt a request."""

    name = "slow_replica"

    def __init__(self, replica: int, start: int, end: int,
                 sleep_s: float, seed=0):
        super().__init__(seed)
        self.replica = replica
        self.start = start
        self.end = end
        self.sleep_s = sleep_s

    def on_step(self, router, step_idx):
        rep = router.replicas[self.replica]
        if self.start <= step_idx < self.end:
            self.fired = True
            rep.delay_s = self.sleep_s
        else:
            rep.delay_s = 0.0


class FlappingReplica(FleetInjector):
    """A replica that is alternately slow and healthy: ``cycles``
    windows of ``slow_for`` stalled router steps every ``period``
    steps, starting at ``start``. Exercises the full breaker loop
    repeatedly — OPEN on misses, half-open probes, CLOSE on recovery,
    OPEN again — asserting the backoff machinery is re-entrant and
    that flapping, like slowness, never loses a request."""

    name = "flapping_replica"

    def __init__(self, replica: int, start: int, period: int,
                 slow_for: int, sleep_s: float, cycles: int = 2,
                 seed=0):
        super().__init__(seed)
        if slow_for >= period:
            raise MXNetError("slow_for must be < period (the replica "
                             "needs healthy steps to flap back up)")
        self.replica = replica
        self.start = start
        self.period = period
        self.slow_for = slow_for
        self.sleep_s = sleep_s
        self.cycles = cycles

    def on_step(self, router, step_idx):
        rep = router.replicas[self.replica]
        rel = step_idx - self.start
        slow = False
        if rel >= 0 and rel // self.period < self.cycles:
            slow = (rel % self.period) < self.slow_for
        if slow:
            self.fired = True
        rep.delay_s = self.sleep_s if slow else 0.0


class FleetCancelStorm(FleetInjector):
    """Router-level cancel storm: same cadence as ``CancelStorm`` but
    through ``Router.cancel`` — cancels land on CLIENT requests
    whether they sit in the router queue or are in flight on a
    replica (where the router must also reclaim the engine-side
    attempt)."""

    name = "fleet_cancel_storm"

    def __init__(self, start: int, every: int = 2, n_per: int = 1,
                 max_cancels: int = 4, seed: int = 0):
        super().__init__(seed)
        self.start = start
        self.every = max(1, int(every))
        self.n_per = int(n_per)
        self.max_cancels = int(max_cancels)
        self.cancelled: List[Request] = []

    def on_step(self, router, step_idx):
        if step_idx < self.start or \
                (step_idx - self.start) % self.every or \
                len(self.cancelled) >= self.max_cancels:
            return
        live = [t.client for t in router._queue] + \
               [t.client for t in router._inflight]
        live = [r for r in live if r.outcome is None]
        if not live:
            return
        n = min(self.n_per, self.max_cancels - len(self.cancelled),
                len(live))
        for i in self.rng.choice(len(live), size=n, replace=False):
            req = live[int(i)]
            if router.cancel(req, detail=f"{self.name} at step "
                                         f"{step_idx}"):
                self.fired = True
                self.cancelled.append(req)
                self._mark(req)
                self.log.append(f"step {step_idx}: cancelled client "
                                f"request {req.request_id} "
                                f"({len(req.token_ids)} tokens in)")


class MigrateFault(FleetInjector):
    """Force ONE live-slot migration (serve/transport.py) with a fault
    injected at a chosen point of the transfer — the
    migration-failure taxonomy of docs/RESILIENCE.md, made runnable.

    At ``at_step`` (deferring until a decode-ready, mid-stream victim
    and a viable destination both exist) the injector arms the
    transport's chaos seam for its ``mode`` and calls
    ``router.migrate``:

      ``none``         no fault — the forced-migration control arm;
                       the transfer must SUCCEED and the continuation
                       stay bit-identical.
      ``kill_source``  the source replica dies mid-capture, BEFORE the
                       slot detaches: capture aborts read-only
                       (MIGRATE_FAIL fallback="none"), and the death
                       path replays everything the source held.
      ``kill_dst``     the destination dies mid-install, AFTER the
                       source detached: the install rolls back its
                       pages, the source custody is released, and the
                       replay fallback re-queues from the delivered
                       suffix (MIGRATE_FAIL fallback="replay").
      ``corrupt``      wire bit rot: one payload byte flips after
                       capture; the destination's crc-chain check
                       refuses the install — replay fallback, loudly.
      ``cancel_race``  the client cancels in the same step the
                       migration is requested. ``order="before"``:
                       migrate must REFUSE the cancelled request and
                       the cancel stands as exactly one CANCELLED
                       terminal; ``order="after"``: the cancel lands
                       on whichever side of the transfer now owns the
                       slot — still exactly one terminal.

    ``affected`` is EMPTY for everything but ``cancel_race`` (its
    victim's stream truncates): every fallback replays bit-identical
    to the fault-free run — migration is an optimisation over replay
    and a failed one may cost only recompute, never correctness."""

    name = "migrate_fault"

    _MODES = ("none", "kill_source", "kill_dst", "corrupt",
              "cancel_race")

    def __init__(self, at_step: int, mode: str = "none",
                 order: str = "before", seed: int = 0):
        super().__init__(seed)
        if mode not in self._MODES:
            raise MXNetError(f"migrate-fault mode {mode!r} not in "
                             f"{'|'.join(self._MODES)}")
        if order not in ("before", "after"):
            raise MXNetError(f"cancel order {order!r} not in "
                             f"before|after")
        self.at_step = at_step
        self.mode = mode
        self.order = order
        self.victim: Optional[Request] = None
        self.src: Optional[int] = None
        self.dst: Optional[int] = None
        self.migrate_returned: Optional[bool] = None

    def _candidate(self, router: Router):
        """A mid-stream victim (decode-ready WITH emitted tokens — the
        fallback must have a non-empty prefix to preserve) plus a
        viable destination, or (None, None) to defer."""
        for t in router._inflight:
            if t.attempt is None or t.attempt.outcome is not None:
                continue
            rep = router.replicas[t.replica]
            if rep.state is not ReplicaState.SERVING or \
                    rep.killed is not None:
                continue
            if not rep.engine.decode_ready(t.attempt.request_id):
                continue
            if not t.attempt.token_ids and not t.client.token_ids:
                continue
            dst = router._migration_dst(t, exclude=t.replica)
            if dst is not None:
                return t, dst
        return None, None

    def on_step(self, router, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        t, dst = self._candidate(router)
        if t is None:
            return                       # defer until one exists
        self.fired = True
        self.victim, self.src, self.dst = t.client, t.replica, dst
        cid = t.client.request_id
        tr = router._transport
        if self.mode == "none":
            self.migrate_returned = router.migrate(cid, dst)
        elif self.mode == "kill_source":
            src_rep = router.replicas[t.replica]

            def die_mid_capture():
                src_rep.kill(f"chaos: source died mid-capture at "
                             f"router step {step_idx}")
                return True

            tr._capture_abort = die_mid_capture
            try:
                self.migrate_returned = router.migrate(cid, dst)
            finally:
                tr._capture_abort = None
        elif self.mode == "kill_dst":
            dst_rep = router.replicas[dst]

            def die_mid_install():
                dst_rep.kill(f"chaos: destination died mid-install "
                             f"at router step {step_idx}")
                return True

            tr._install_abort = die_mid_install
            try:
                self.migrate_returned = router.migrate(cid, dst)
            finally:
                tr._install_abort = None
        elif self.mode == "corrupt":
            byte = int(self.rng.randint(256))
            tr._capsule_hook = \
                lambda c: c.corrupt(page_idx=0, byte=byte)
            try:
                self.migrate_returned = router.migrate(cid, dst)
            finally:
                tr._capsule_hook = None
        else:                            # cancel_race
            self._mark(t.client)
            if self.order == "before":
                router.cancel(t.client,
                              detail=f"{self.name}: cancel-then-"
                                     f"migrate at step {step_idx}")
                self.migrate_returned = router.migrate(cid, dst)
                if self.migrate_returned:
                    raise MXNetError(
                        "migrate accepted a cancelled request — the "
                        "race the refusal ladder exists to lose")
            else:
                self.migrate_returned = router.migrate(cid, dst)
                router.cancel(t.client,
                              detail=f"{self.name}: migrate-then-"
                                     f"cancel at step {step_idx}")
        self.log.append(
            f"step {step_idx}: {self.mode} migration of request "
            f"{cid} replica{self.src}->replica{dst} returned "
            f"{self.migrate_returned}")


class ScaleDownRace(FleetInjector):
    """The membership race: remove one replica and admit a fresh one
    in the SAME fleet pass — scale-down racing scale-up. The drain
    must route its migrations around the newcomer's WARMING state (or
    into it: spill-class work may land there), every request must
    still reach exactly one terminal, and the retiring replica's
    tombstone must keep every older index stable. ``spawn`` is a
    zero-arg engine factory (the supervisor's contract)."""

    name = "scale_down_race"

    def __init__(self, victim: int, spawn, at_step: int, seed=0):
        super().__init__(seed)
        self.victim = victim
        self.spawn = spawn
        self.at_step = at_step
        self.added: Optional[int] = None

    def on_step(self, router, step_idx):
        if self.fired or step_idx < self.at_step:
            return
        rep = router.replicas[self.victim]
        if rep.state is not ReplicaState.SERVING:
            return                           # defer to a clean fire
        self.fired = True
        stats = router.remove_replica(self.victim)
        self.added = router.add_replica(self.spawn())
        self.log.append(
            f"step {step_idx}: remove_replica({self.victim}) "
            f"(migrated={stats['migrated']} requeued="
            f"{stats['requeued']} remaining={stats['remaining']}) "
            f"racing add_replica -> {self.added}")


class DrainKill(FleetInjector):
    """Replica death MID-DRAIN: ``remove_replica`` at ``at_step``,
    then — ``kill_after`` router steps later, while the victim is
    still DRAINING — the host disappears. Whatever the drain had not
    yet migrated must come back through the death path's replay
    re-queue: zero lost requests either way, and the drain's
    finalisation must simply never happen (DEAD wins over RETIRED)."""

    name = "drain_kill"

    def __init__(self, victim: int, at_step: int, kill_after: int = 2,
                 seed=0):
        super().__init__(seed)
        self.victim = victim
        self.at_step = at_step
        self.kill_after = kill_after
        self.removed_at: Optional[int] = None
        self.killed_mid_drain = False

    def on_step(self, router, step_idx):
        if self.fired:
            return
        rep = router.replicas[self.victim]
        if self.removed_at is None:
            if step_idx < self.at_step:
                return
            if rep.state is not ReplicaState.SERVING:
                return
            stats = router.remove_replica(self.victim)
            self.removed_at = step_idx
            self.log.append(
                f"step {step_idx}: draining replica {self.victim} "
                f"(remaining={stats['remaining']})")
            return
        if step_idx < self.removed_at + self.kill_after:
            return
        self.fired = True
        if rep.state is ReplicaState.DRAINING and rep.killed is None:
            rep.kill(f"chaos kill mid-drain at router step {step_idx}")
            self.killed_mid_drain = True
            self.log.append(
                f"step {step_idx}: killed replica {self.victim} "
                f"mid-drain")
        else:
            self.log.append(
                f"step {step_idx}: drain already finalised "
                f"({rep.state}) — kill skipped")


class SupervisorChaos(FleetInjector):
    """Drives a ``FleetSupervisor`` from the chaos hook — one
    ``tick()`` per router step — optionally arming a rolling upgrade
    at ``upgrade_at``, and modelling the supervisor PROCESS dying at
    ``kill_at``: from that step on it never ticks again. The contract
    under test is the router-owned finalisation: the replica the roll
    had mid-drain still finishes its warm_start on the router's own
    step loop, the fleet serves on, and only the not-yet-started
    targets stay on old weights."""

    name = "supervisor_kill"

    def __init__(self, supervisor, kill_at: Optional[int] = None,
                 upgrade_at: Optional[int] = None,
                 upgrade_src: Optional[dict] = None, seed=0):
        super().__init__(seed)
        self.supervisor = supervisor
        self.kill_at = kill_at
        self.upgrade_at = upgrade_at
        self.upgrade_src = upgrade_src or {}
        self.killed_at_step: Optional[int] = None
        self.upgrade_started = False

    def on_step(self, router, step_idx):
        if self.killed_at_step is not None:
            return                           # the supervisor is gone
        if self.kill_at is not None and step_idx >= self.kill_at:
            self.killed_at_step = step_idx
            self.fired = True
            roll = self.supervisor.snapshot()["roll"]
            self.log.append(
                f"step {step_idx}: supervisor killed (roll state at "
                f"death: {roll})")
            return
        if self.upgrade_at is not None and not self.upgrade_started \
                and step_idx >= self.upgrade_at:
            self.supervisor.start_upgrade(**self.upgrade_src)
            self.upgrade_started = True
            self.log.append(
                f"step {step_idx}: rolling upgrade armed")
        self.supervisor.tick()


def _mirror_injector_events(flight, component, injectors, seen):
    """Land every injector firing on the flight-recorder timeline —
    one CHAOS event per new injector-log line, so a postmortem dump
    always NAMES the injected fault next to its consequences (the
    obssmoke CI contract). ``seen`` maps injector → log length already
    mirrored; injectors stay recorder-agnostic."""
    for inj in injectors:
        n = seen.get(id(inj), 0)
        for line in inj.log[n:]:
            flight.emit(component, EventType.CHAOS, entity=inj.name,
                        detail=line[:300])
        seen[id(inj)] = len(inj.log)


def run_fleet_chaos(router: Router, requests, injectors,
                    arrival_times=None, audit_every_step: bool = True,
                    poll_sleep: float = 1e-3):
    """Drive ``requests`` through the fleet with ``injectors`` firing
    via the router's ``before_step`` hook, auditing EVERY surviving
    replica's page invariant after every router step (a dead replica's
    memory is off-limits by definition). Raises if any request fails
    to reach a terminal outcome — after dumping a postmortem of the
    fleet timeline (the chaos-invariant-breach black box,
    docs/OBSERVABILITY.md)."""
    seen: dict = {}

    def before(rt, i):
        for inj in injectors:
            inj.on_step(rt, i)
        _mirror_injector_events(rt.flight, "router", injectors, seen)

    def after(rt, i):
        if audit_every_step:
            for rep in rt.replicas:
                if rep.state is not ReplicaState.DEAD and \
                        rep.killed is None:
                    rep.engine.audit_pages()

    try:
        router.run(requests, arrival_times=arrival_times,
                   poll_sleep=poll_sleep, before_step=before,
                   after_step=after)
        assert_all_terminal(requests)
    except MXNetError as e:
        router.flight.postmortem(
            "chaos invariant breach", f"{type(e).__name__}",
            context={"error": str(e)[:400]})
        raise
    return requests


def assert_fleet_health_consistent(router: Router, requests):
    """The router's outcome tally must equal the per-request outcomes
    — the fleet twin of ``assert_health_consistent`` (the engines'
    own counters count ATTEMPTS, which legitimately exceed client
    requests under requeue; the router's count client terminals)."""
    tally = {o.value: 0 for o in Outcome}
    for r in requests:
        tally[r.outcome.value] += 1
    if tally != router.health:
        raise MXNetError(f"router health {router.health} != outcome "
                         f"tally {tally}")
    by_tier = _tier_tally(requests)
    if by_tier != router.health_by_tier:
        raise MXNetError(f"router per-tier health "
                         f"{router.health_by_tier} != per-tier tally "
                         f"{by_tier}")


def run_chaos(engine: InferenceEngine, requests, injectors,
              arrival_times=None, audit_every_step: bool = True,
              poll_sleep: float = 1e-3):
    """Drive ``requests`` through ``engine`` with ``injectors`` firing
    via the scheduler's ``before_step`` hook, auditing the page
    invariant after EVERY step (faults included). Returns the requests;
    raises if any request failed to reach a terminal outcome — after
    dumping a postmortem of the engine timeline (the
    chaos-invariant-breach black box, docs/OBSERVABILITY.md)."""
    seen: dict = {}

    def before(eng, i):
        for inj in injectors:
            inj.on_step(eng, i)
        _mirror_injector_events(eng.flight, eng._component, injectors,
                                seen)

    def after(eng, i):
        if audit_every_step:
            eng.audit_pages()

    try:
        engine.run(requests, arrival_times=arrival_times,
                   poll_sleep=poll_sleep, before_step=before,
                   after_step=after)
        assert_all_terminal(requests)
    except MXNetError as e:
        engine.flight.postmortem(
            "chaos invariant breach", f"{type(e).__name__}",
            context={"error": str(e)[:400]})
        raise
    return requests


def assert_all_terminal(requests):
    missing = [i for i, r in enumerate(requests) if r.outcome is None]
    if missing:
        raise MXNetError(f"requests {missing} did not reach a terminal "
                         f"outcome — the engine failed quiescence")


def _tier_tally(requests):
    from .slo import Tier
    by_tier = {t.value: {o.value: 0 for o in Outcome} for t in Tier}
    for r in requests:
        by_tier[r.tier.value][r.outcome.value] += 1
    return by_tier


def assert_health_consistent(engine: InferenceEngine, requests):
    """The engine's health counters must equal the per-request outcome
    tally — a counter drifting from the outcomes it summarizes would
    lie to the operator exactly when it matters. The per-tier split
    (the /metrics surface) must agree too."""
    tally = {o.value: 0 for o in Outcome}
    for r in requests:
        tally[r.outcome.value] += 1
    if tally != engine.health:
        raise MXNetError(f"health counters {engine.health} != outcome "
                         f"tally {tally}")
    by_tier = _tier_tally(requests)
    if by_tier != engine.health_by_tier:
        raise MXNetError(f"per-tier health {engine.health_by_tier} != "
                         f"per-tier tally {by_tier}")
