"""Base utilities: errors, env-var config, registries.

TPU-native re-design of the reference's base plumbing:
  - ``MXNetError`` mirrors the exception type surfaced through the reference's
    C ABI (`src/c_api/c_api.cc`, `MXGetLastError`; file-level citation — see
    SURVEY.md provenance caveat).
  - ``getenv_*`` mirrors `dmlc::GetEnv` (`3rdparty/dmlc-core/include/dmlc/
    parameter.h`) but under a single ``MXTPU_*`` namespace (SURVEY.md §5.6).

There is no FFI boundary here: JAX/XLA is the native substrate, so the "C API"
layer of the reference collapses into ordinary Python calls that dispatch
straight into XLA's async runtime.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "MXNetError",
    "DeferredInitializationError",
    "getenv_int",
    "getenv_bool",
    "getenv_str",
    "shard_map",
    "string_types",
    "numeric_types",
    "integer_types",
]

# jax moved shard_map out of experimental around 0.4.35→0.6 (first as
# ``jax.shard_map``, keeping the experimental alias for a while). Resolve
# it ONCE here; everything in this package imports the symbol from base so
# the framework runs on either side of the move.
try:
    from jax import shard_map as _jax_shard_map
    shard_map = _jax_shard_map.shard_map if hasattr(
        _jax_shard_map, "shard_map") else _jax_shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map  # noqa: F401


def pcast_varying(x, axes):
    """Compat for ``lax.pcast(x, axes, to="varying")`` (the VMA branding
    newer jax requires on loop carries inside shard_map). Older jax has no
    varying-manual-axes tracking, where the cast is semantically the
    identity."""
    from jax import lax as _lax
    if hasattr(_lax, "pcast"):
        return _lax.pcast(x, axes, to="varying")
    return x


class MXNetError(RuntimeError):
    """Default error thrown by framework functions.

    The reference translates C++ exceptions into error codes at the C ABI and
    rethrows ``MXNetError`` in Python (`python/mxnet/base.py`). Here errors
    propagate natively, but we keep the type so user code catching
    ``MXNetError`` keeps working.
    """


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape could be inferred.

    Mirrors `python/mxnet/gluon/parameter.py`'s deferred-init contract.
    """


string_types = (str,)
numeric_types = (float, int, bool)
integer_types = (int,)

_ENV_PREFIXES = ("MXTPU_", "MXNET_")


def _getenv_raw(name: str) -> Optional[str]:
    """Look up ``name`` under the MXTPU_ namespace, falling back to MXNET_
    for compatibility with reference env-var spellings (SURVEY.md §5.6)."""
    for prefix in _ENV_PREFIXES:
        for candidate in (name, prefix + name):
            if candidate.startswith(prefix) or candidate == name:
                val = os.environ.get(candidate)
                if val is not None:
                    return val
    return None


def getenv_str(name: str, default: str = "") -> str:
    val = _getenv_raw(name)
    return default if val is None else val


def getenv_int(name: str, default: int = 0) -> int:
    val = _getenv_raw(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        return default


def getenv_bool(name: str, default: bool = False) -> bool:
    val = _getenv_raw(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


class Registry:
    """A tiny named registry, the analogue of ``dmlc::Registry``
    (`3rdparty/dmlc-core/include/dmlc/registry.h`)."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, name: str, obj: Any = None, *, aliases: tuple = ()):
        def _do(o):
            key = name.lower()
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def get(self, name: str) -> Any:
        key = name.lower()
        if key not in self._entries:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(set(self._entries))}"
            )
        return self._entries[key]

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._entries

    def list(self) -> List[str]:
        return sorted(self._entries)
