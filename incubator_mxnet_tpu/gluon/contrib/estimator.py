"""Estimator fit loop (re-design of
`python/mxnet/gluon/contrib/estimator/estimator.py` (≥1.6) — file-level
citation, SURVEY.md caveat).

One high-level train driver over (net, loss, metrics, trainer) with an
event-handler protocol: handlers implement any of ``train_begin``,
``epoch_begin``, ``batch_begin``, ``batch_end``, ``epoch_end``,
``train_end``."""

from __future__ import annotations

import time
from typing import List, Optional

from ...base import MXNetError
from ... import autograd
from ... import metric as _metric_mod
from .. import Trainer, loss as _loss_mod

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "StopTraining"]


class StopTraining(Exception):
    """Raised by a handler to end fit() early (early stopping)."""


class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator):
        pass


class BatchEnd:
    def batch_end(self, estimator):
        pass


class LoggingHandler(TrainBegin, EpochEnd, BatchEnd):
    """Throughput + metric logging (the Speedometer analogue)."""

    def __init__(self, log_interval=50):
        self.log_interval = log_interval
        self._tick = None
        self._samples = 0

    def train_begin(self, est):
        self._tick = time.time()

    def batch_end(self, est):
        self._samples += est.last_batch_size
        if est.batch_idx % self.log_interval == 0:
            dt = max(time.time() - self._tick, 1e-9)
            vals = ", ".join(f"{n}={v:.4f}"
                             for n, v in est.train_metrics_values())
            est.logger(f"epoch {est.epoch} batch {est.batch_idx}: "
                       f"{self._samples / dt:.1f} samples/s {vals}")
            self._tick, self._samples = time.time(), 0

    def epoch_end(self, est):
        vals = ", ".join(f"{n}={v:.4f}"
                         for n, v in est.train_metrics_values())
        est.logger(f"epoch {est.epoch} done: {vals}")


class CheckpointHandler(EpochEnd):
    """Save params each epoch (parity: estimator CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model"):
        self.model_dir = model_dir
        self.model_prefix = model_prefix

    def epoch_end(self, est):
        import os
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-{est.epoch:04d}.params")
        est.net.save_parameters(path)
        est.logger(f"saved checkpoint {path}")


class EarlyStoppingHandler(EpochEnd):
    """Stop when a monitored metric stops improving."""

    def __init__(self, monitor="loss", mode="min", patience=2):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self._best = None
        self._bad = 0

    def epoch_end(self, est):
        vals = dict(est.train_metrics_values())
        if self.monitor not in vals:
            return
        v = vals[self.monitor]
        better = self._best is None or \
            (v < self._best if self.mode == "min" else v > self._best)
        if better:
            self._best, self._bad = v, 0
        else:
            self._bad += 1
            if self._bad >= self.patience:
                raise StopTraining


class Estimator:
    """fit() driver (parity: gluon.contrib.estimator.Estimator)."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, logger=print):
        self.net = net
        self.loss = loss if isinstance(loss, _loss_mod.Loss) else loss
        metrics = train_metrics or []
        if not isinstance(metrics, (list, tuple)):
            metrics = [metrics]
        self.train_metrics = [
            m if isinstance(m, _metric_mod.EvalMetric)
            else _metric_mod.create(m) for m in metrics]
        self._loss_metric = _metric_mod.Loss()
        self.trainer = trainer
        self.logger = logger
        self.epoch = 0
        self.batch_idx = 0
        self.last_batch_size = 0

    def train_metrics_values(self):
        out = list(zip(*[("loss",), (self._loss_metric.get()[1],)]))
        vals = [("loss", self._loss_metric.get()[1])]
        for m in self.train_metrics:
            vals.append(m.get_name_value()[0])
        return vals

    def _dispatch(self, handlers, event):
        for h in handlers:
            fn = getattr(h, event, None)
            if fn is not None:
                fn(self)

    def evaluate(self, val_data, metrics=None):
        metrics = metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            if hasattr(batch, "data"):
                data, label = batch.data[0], batch.label[0]
            else:
                data, label = batch[0], batch[1]
            out = self.net(data)
            for m in metrics:
                m.update([label], [out])
        return [m.get_name_value()[0] for m in metrics]

    def fit(self, train_data, val_data=None, epochs=1,
            event_handlers: Optional[List] = None, batch_axis=0):
        if self.trainer is None:
            self.trainer = Trainer(self.net.collect_params(), "sgd",
                                   {"learning_rate": 0.01})
        handlers = list(event_handlers or [])
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())
        try:
            self._dispatch(handlers, "train_begin")
            for epoch in range(epochs):
                self.epoch = epoch
                self._loss_metric.reset()
                for m in self.train_metrics:
                    m.reset()
                if hasattr(train_data, "reset"):
                    train_data.reset()
                self._dispatch(handlers, "epoch_begin")
                for i, batch in enumerate(train_data):
                    self.batch_idx = i
                    if hasattr(batch, "data"):
                        data, label = batch.data[0], batch.label[0]
                    else:
                        data, label = batch[0], batch[1]
                    self.last_batch_size = data.shape[batch_axis]
                    self._dispatch(handlers, "batch_begin")
                    with autograd.record():
                        out = self.net(data)
                        l = self.loss(out, label)
                    l.backward()
                    self.trainer.step(self.last_batch_size)
                    self._loss_metric.update(None, [l])
                    for m in self.train_metrics:
                        m.update([label], [out])
                    self._dispatch(handlers, "batch_end")
                if val_data is not None:
                    for name, v in self.evaluate(val_data):
                        self.logger(f"epoch {epoch} validation "
                                    f"{name}={v:.4f}")
                self._dispatch(handlers, "epoch_end")
        except StopTraining:
            self.logger(f"early stop at epoch {self.epoch}")
        self._dispatch(handlers, "train_end")
        return self
