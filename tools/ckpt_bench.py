"""Checkpointing overhead benchmark: async snapshots vs sync saves vs none.

Measures the steady-state per-step cost the elastic checkpoint
subsystem (checkpoint/) adds to an SPMD train loop, against the
pre-subsystem behavior — a blocking whole-tree ``save_ndarrays`` on the
critical path every save interval. The async path's only critical-path
work is the device→host gather; serialization and disk I/O run on a
deprioritized writer thread, so its overhead must stay **< 5%** (the
acceptance bar; sync is shown for contrast). CPU-measurable by design.

Methodology (the effect is smaller than CPU wall-clock jitter, so raw
A/B run comparison is hopeless): ONE trainer per mode runs ALTERNATING
windows — a plain window of ``--window`` steps, then an identical
window whose ``--every``-th steps carry a save — and the overhead is
the MEDIAN over paired (save_window / adjacent plain_window) ratios.
Adjacent windows are ~1s apart, so machine drift cancels in each pair;
the median filters scheduler spikes. ``save_step_ms`` isolates the step
that carries the save: sync blocks there (serialize on the critical
path), async pays only the gather.

``--smoke`` (wired into ci/run.sh as the ``ckptbench`` stage) runs a
fast structural guard: snapshots commit while stepping, the previous
manifest stays loadable, and a mid-run capsule restores into a fresh
trainer BIT-EXACTLY (next-step losses identical).

Usage:
  python tools/ckpt_bench.py                 # full bench, banks JSON
  python tools/ckpt_bench.py --smoke         # CI guard (fast, asserts)
  python tools/ckpt_bench.py --json OUT.json
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build(units, layers, seed=0):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.Sequential()
    for _ in range(layers):
        net.add(nn.Dense(units, in_units=units))
    net.initialize()
    tr = parallel.SPMDTrainer(
        net, loss=lambda o, y: ((o - y) ** 2).mean(),
        optimizer="adam", optimizer_params={"learning_rate": 1e-3})
    return net, tr


def _batch(units, batch):
    import numpy as np
    from incubator_mxnet_tpu import nd
    rng = np.random.RandomState(0)
    return (nd.array(rng.randn(batch, units).astype(np.float32)),
            nd.array(rng.randn(batch, units).astype(np.float32)))


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def measure_mode(mode, units, layers, batch, window, every, pairs,
                 warmup=3):
    """Alternating plain/save windows on one trainer; paired ratios."""
    import jax
    from incubator_mxnet_tpu import checkpoint as ckpt
    from incubator_mxnet_tpu.utils.serialization import save_ndarrays

    net, tr = _build(units, layers)
    x, y = _batch(units, batch)
    ckdir = tempfile.mkdtemp(prefix=f"ckbench_{mode}_")
    mgr = ckpt.CheckpointManager(ckdir, keep=2) if mode == "async" else None
    saves = 0

    def run_window(with_saves):
        nonlocal saves
        step_times, save_steps = [], []
        t0 = time.perf_counter()
        for s in range(window):
            is_save = with_saves and (s + 1) % every == 0
            ts = time.perf_counter()
            L = tr.step(x, y)
            if is_save:
                if mode == "async":
                    tr.save_checkpoint(mgr)
                else:
                    # the pre-subsystem critical path: host the whole
                    # tree and serialize it before stepping on
                    tree, _meta = ckpt.spmd_capsule(tr)
                    save_ndarrays(os.path.join(ckdir, "sync.params"),
                                  {k: v for k, v in tree.items()})
                saves += 1
            jax.block_until_ready(L._data)
            dt = time.perf_counter() - ts
            step_times.append(dt)
            if is_save:
                save_steps.append(dt)
        if with_saves and mgr is not None:
            mgr.wait()                   # drain: charge the tail honestly
        total = time.perf_counter() - t0
        return total / window, step_times, save_steps

    try:
        for _ in range(warmup):
            jax.block_until_ready(tr.step(x, y)._data)
        ratios, plain_means, save_means = [], [], []
        all_steps, all_save_steps = [], []
        for _ in range(pairs):
            plain, st_p, _ = run_window(False)
            saving, st_s, ss = run_window(True)
            ratios.append(saving / plain)
            plain_means.append(plain)
            save_means.append(saving)
            all_steps += st_p + st_s
            all_save_steps += ss
        committed = len(mgr.all_steps()) if mgr else (1 if saves else 0)
    finally:
        if mgr:
            mgr.close()
        shutil.rmtree(ckdir, ignore_errors=True)

    all_steps.sort()
    return {
        "plain_window_step_ms": _median(plain_means) * 1e3,
        "save_window_step_ms": _median(save_means) * 1e3,
        "overhead_pct": (_median(ratios) - 1.0) * 100.0,
        "save_step_ms": (_median(all_save_steps) * 1e3
                         if all_save_steps else None),
        "median_step_ms": _median(all_steps) * 1e3,
        "p99_step_ms": all_steps[
            min(len(all_steps) - 1, int(len(all_steps) * 0.99))] * 1e3,
        "saves": saves,
        "committed": committed,
    }


def smoke():
    """Structural CI guard — fast, assertion-based."""
    from incubator_mxnet_tpu import checkpoint as ckpt

    units, layers, batch = 64, 2, 32
    net, tr = _build(units, layers, seed=0)
    x, y = _batch(units, batch)
    ckdir = tempfile.mkdtemp(prefix="ckbench_smoke_")
    mgr = ckpt.CheckpointManager(ckdir, keep=2)
    ok = True
    try:
        ref = []
        for s in range(6):
            ref.append(float(tr.step(x, y).asnumpy()))
            if s == 2:
                tr.save_checkpoint(mgr)    # async, mid-run
        mgr.wait()
        if mgr.all_steps() != [3]:
            print(f"FAIL: expected committed step [3], got "
                  f"{mgr.all_steps()}", file=sys.stderr)
            ok = False
        _, tr2 = _build(units, layers, seed=9)
        got = tr2.restore_checkpoint(mgr)
        res = [float(tr2.step(x, y).asnumpy()) for _ in range(3)]
        if res != ref[3:]:
            print(f"FAIL: capsule resume not bit-exact: {res} vs "
                  f"{ref[3:]}", file=sys.stderr)
            ok = False
        else:
            print(f"smoke: resume from step {got} bit-exact over "
                  f"{len(res)} steps; async commit + GC OK")
    finally:
        mgr.close()
        shutil.rmtree(ckdir, ignore_errors=True)
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI guard: commit + bit-exact resume")
    ap.add_argument("--json", default=None,
                    help="bank results here (default BENCH_CKPT.json at "
                         "the repo root for a full run)")
    ap.add_argument("--units", type=int, default=512)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--window", type=int, default=20,
                    help="steps per measurement window")
    ap.add_argument("--every", type=int, default=20,
                    help="save interval within a save window (steps)")
    ap.add_argument("--pairs", type=int, default=8,
                    help="plain/save window pairs per mode")
    args = ap.parse_args()

    if args.smoke:
        sys.exit(0 if smoke() else 1)

    cfg = dict(units=args.units, layers=args.layers, batch=args.batch,
               window=args.window, every=args.every, pairs=args.pairs)
    async_ = measure_mode("async", **cfg)
    sync = measure_mode("sync", **cfg)

    result = {
        "config": {**cfg,
                   "backend": os.environ.get("JAX_PLATFORMS", "cpu")},
        "async": async_,
        "sync_save_ndarrays": sync,
    }
    print(json.dumps(result, indent=2))

    ok = True
    if async_["overhead_pct"] >= 5.0:
        print(f"FAIL: async checkpoint overhead "
              f"{async_['overhead_pct']:.1f}% >= 5% bar",
              file=sys.stderr)
        ok = False
    if async_["committed"] < 1:
        print("FAIL: async run committed no snapshots", file=sys.stderr)
        ok = False

    out = args.json
    if out is None:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_CKPT.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"banked {out}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
