"""Async double-buffered checkpoint manager.

The step loop's critical path pays ONLY the device→host gather of the
state pytree (host-transfer DMAs are kicked off for every leaf first,
then materialized — on TPU the copies overlap); serialization and disk
I/O run on a bounded background writer thread. At most ONE snapshot is
in flight: ``save(step=N+1)`` waits for N's *write* only if it has not
finished yet, so with any sane save interval step N+1 never blocks on
step N's disk I/O (tools/ckpt_bench.py measures the steady-state
overhead; BENCH_CKPT.json banks it).

Commit is atomic (manifest.py) and GC keeps the last ``keep`` committed
steps. ``install_preemption_hook`` arms a SIGTERM handler that drains
the in-flight snapshot and writes a final synchronous one before the
process dies — the preemptible-TPU-pod contract (docs/CHECKPOINTING.md).

Transient IO errors (an NFS blip, a full-then-GC'd disk, a flaky
object-store fuse mount) are retried with bounded exponential backoff
before the error latches: ``MXTPU_CKPT_RETRY_ATTEMPTS`` (default 3)
total attempts, ``MXTPU_CKPT_RETRY_BACKOFF`` (default 0.1 s) base
delay, doubling per retry. ``MXTPU_CKPT_FAIL_WRITES=n`` fault-injects
``n`` transient failures (one per attempt) for tests — n failures
under the attempt bound still commit; n >= the bound latches the error
exactly as a persistent outage would (docs/RESILIENCE.md).
"""

from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..base import MXNetError
from . import manifest as _manifest

__all__ = ["CheckpointManager", "gather_tree"]


def _unwrap(leaf):
    """NDArray / jax.Array / np.ndarray → the underlying array value."""
    if hasattr(leaf, "_data"):          # NDArray without importing ndarray
        leaf = leaf._data
    return leaf


def _sharding_spec_str(arr) -> Optional[str]:
    try:
        sh = arr.sharding
        spec = getattr(sh, "spec", None)
        return None if spec is None else str(spec)
    except Exception:
        return None


def _full_index(shape):
    return [(0, int(s)) for s in shape]


def gather_tree(tree: Dict[str, object]) -> Dict[str, dict]:
    """Device→host gather of a flat name→array tree into manifest
    entries, deduplicated to this process's replica-0 addressable
    shards (each unique piece of global data is written exactly once
    across the job).

    The gather is two-phase: phase 1 kicks off a non-blocking
    device→host transfer for every leaf (``copy_to_host_async``), phase
    2 materializes numpy views — so on real hardware the per-leaf DMAs
    overlap instead of serializing.
    """
    leaves = {name: _unwrap(leaf) for name, leaf in tree.items()}
    for arr in leaves.values():         # phase 1: start all the DMAs
        if hasattr(arr, "copy_to_host_async"):
            try:
                arr.copy_to_host_async()
            except Exception:
                pass
    entries: Dict[str, dict] = {}
    for name, arr in leaves.items():    # phase 2: materialize
        if isinstance(arr, (bool, int, float)):
            arr = np.asarray(arr)
        if isinstance(arr, np.ndarray):
            entries[name] = {"shape": arr.shape,
                             "dtype": _manifest._dtype_name(arr),
                             "spec": None,
                             "shards": [(_full_index(arr.shape),
                                         np.ascontiguousarray(arr))]}
            continue
        spec = _sharding_spec_str(arr)
        shards = []
        try:
            addressable = list(arr.addressable_shards)
        except Exception:
            addressable = []
        multi = len(addressable) > 1 or jax.process_count() > 1
        if addressable and multi:
            for sh in addressable:
                if sh.replica_id != 0:
                    continue            # another device/process owns it
                idx = []
                for sl, dim in zip(sh.index, arr.shape):
                    start = 0 if sl.start is None else int(sl.start)
                    stop = int(dim) if sl.stop is None else int(sl.stop)
                    idx.append((start, stop))
                shards.append((idx, np.asarray(sh.data)))
        else:
            shards.append((_full_index(arr.shape), np.asarray(arr)))
        host_dtype = _manifest._dtype_name(shards[0][1]) if shards \
            else str(arr.dtype)
        entries[name] = {"shape": tuple(int(s) for s in arr.shape),
                         "dtype": host_dtype, "spec": spec,
                         "shards": shards}
    return entries


class CheckpointManager:
    """Directory of committed ``step_<N>`` snapshots with async writes.

    Parameters
    ----------
    directory : checkpoint root (created if missing).
    keep : keep-last-k garbage collection after each commit (None/0 =
        keep everything).
    async_save : write snapshots on the background thread (default);
        ``False`` forces every save onto the critical path (the sync
        baseline of tools/ckpt_bench.py).
    """

    def __init__(self, directory: str, keep: Optional[int] = 3,
                 async_save: bool = True, recorder=None):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep or 0
        self.async_save = async_save
        # flight recorder (events.py): every COMMIT is one
        # event — pass the owning engine/trainer's recorder to land
        # commits on the same timeline as the steps they snapshot.
        # The emit may run on the writer thread; a deque append is
        # atomic under the GIL, same contract as the counters below.
        from ..events import resolve_recorder
        self.flight = resolve_recorder(recorder, histograms=False)
        # RLock: the SIGTERM preemption handler runs ON the main thread
        # and may interrupt save() INSIDE its critical section; the
        # handler's drain (wait()) must be able to re-enter. Condition
        # fully releases an RLock across wait() (via _release_save), so
        # the writer thread still makes progress.
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._pending: Optional[Tuple] = None   # (step, entries, meta)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._sig_prev = None
        self.committed_steps = 0                # cumulative commits
        self.write_retries = 0                  # transient IO retries
        self.restore_fallbacks = 0              # corrupt-latest fallbacks
        self._injected_failures = 0             # MXTPU_CKPT_FAIL_WRITES

    # -- background writer -------------------------------------------- #
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="mxtpu-ckpt-writer")
            self._thread.start()

    def _writer_loop(self):
        try:
            # deprioritize the writer: on hosts where compute shares the
            # cores (CPU backend; TPU hosts during input pipelines), the
            # background serialize must lose scheduler contests against
            # the step loop, not split them evenly
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 10)
        except (OSError, AttributeError):
            pass
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    # bounded wait: a SIGTERM handler can interrupt
                    # save() between setting _pending and notify_all —
                    # the notify is then lost while the handler itself
                    # blocks in wait(); the timeout turns that lost
                    # wakeup into at most a 200 ms stall instead of a
                    # drain deadlock at the preemption deadline
                    self._cv.wait(timeout=0.2)
                if self._pending is None and self._closed:
                    return
                job = self._pending
            try:
                self._write(*job)
            except BaseException as e:          # surfaced on next call
                with self._cv:
                    self._error = e
            finally:
                with self._cv:
                    self._pending = None
                    self._cv.notify_all()

    def _maybe_inject_write_failure(self):
        """``MXTPU_CKPT_FAIL_WRITES=n``: the first n write ATTEMPTS (not
        snapshots) raise a transient OSError — the deterministic fault
        the retry loop is tested against."""
        budget = int(os.environ.get("MXTPU_CKPT_FAIL_WRITES", "0") or 0)
        with self._lock:
            if self._injected_failures >= budget:
                return
            self._injected_failures += 1
            count = self._injected_failures
        raise OSError(
            f"injected transient checkpoint write failure "
            f"({count}/{budget})")

    def _write(self, step, entries, meta):
        """One snapshot write with bounded exponential-backoff retry on
        TRANSIENT IO errors (OSError); structural errors (MXNetError —
        e.g. the step already committed) are never retried. After the
        last attempt the error propagates and latches exactly as
        before."""
        attempts = max(1, int(os.environ.get(
            "MXTPU_CKPT_RETRY_ATTEMPTS", "3") or 3))
        backoff = float(os.environ.get(
            "MXTPU_CKPT_RETRY_BACKOFF", "0.1") or 0.1)
        for attempt in range(attempts):
            try:
                self._maybe_inject_write_failure()
                self._write_once(step, entries, meta)
                return
            except MXNetError:
                raise
            except OSError:
                if attempt + 1 >= attempts:
                    raise
                # counter shared with main-thread scrapers (ckpt_bench,
                # chaos assertions): RLock'd so a torn read-modify-write
                # on the writer thread cannot drop a retry
                with self._lock:
                    self.write_retries += 1
                time.sleep(backoff * (2 ** attempt))

    def _write_once(self, step, entries, meta):
        _manifest.write_step(
            self.directory, step, entries, meta=meta,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            sync_fn=self._process_barrier)
        # commit count is read by the step loop / benches while the
        # writer thread bumps it — same RLock as the rest of the
        # shared state (mxlint lock-discipline)
        with self._lock:
            self.committed_steps += 1
        from ..events import EventType
        self.flight.emit("checkpoint", EventType.CHECKPOINT_COMMIT,
                         entity=self.directory, step=int(step),
                         preempted=bool(meta.get("preempted", False)))
        if self.keep:
            _manifest.gc_steps(self.directory, self.keep)

    @staticmethod
    def _process_barrier():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("mxtpu_ckpt_commit")

    def _raise_pending_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError(
                f"background checkpoint write failed: {err!r}") from err

    # -- public API ---------------------------------------------------- #
    def save(self, step: int, tree: Dict[str, object],
             meta: Optional[dict] = None, block: bool = False) -> None:
        """Snapshot ``tree`` (flat name→array) at ``step``.

        Gathers device state to host on the caller thread (the only
        critical-path cost), then hands off to the writer. With
        ``block=True`` (or ``async_save=False``) the write itself also
        runs here — used for final preemption saves and as the sync
        baseline in benchmarks.
        """
        if self._closed:
            raise MXNetError("CheckpointManager is closed")
        entries = gather_tree(tree)
        meta = dict(meta or {})
        if not (self.async_save and not block):
            self.wait()
            self._write(step, entries, meta)
            self._raise_pending_error()
            return
        self._ensure_thread()
        with self._cv:
            while self._pending is not None:    # bound: one in flight
                self._cv.wait()
            self._raise_pending_error()
            self._pending = (step, entries, meta)
            self._cv.notify_all()

    def wait(self) -> None:
        """Drain the in-flight snapshot (no-op when idle)."""
        if self._thread is None:
            self._raise_pending_error()
            return
        with self._cv:
            while self._pending is not None:
                self._cv.wait()
        self._raise_pending_error()

    def all_steps(self) -> List[int]:
        return _manifest.list_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, fallback: bool = True
                ) -> Tuple[Dict[str, np.ndarray], dict]:
        """Load a committed step (default: latest) → (arrays, meta).

        When restoring the LATEST step and it turns out unreadable — a
        corrupt shard (crc32 mismatch), a truncated file, a missing
        piece — the restore falls back to the previous committed step,
        walking back through everything keep-last-k retained, and
        WARNS loudly naming the bad shard each time (this is what
        keep-last-k is for: an auto-resume must prefer losing a few
        steps over failing the whole run — docs/RESILIENCE.md).
        ``fallback=False``, or an EXPLICIT ``step``, restores the old
        fail-loud behavior (an operator asking for step N wants step N
        or the error).
        """
        if step is not None:
            return _manifest.load_step(self.directory, step)
        steps = self.all_steps()
        if not steps:
            raise MXNetError(
                f"no committed checkpoint under {self.directory}")
        if not fallback:
            return _manifest.load_step(self.directory, steps[-1])
        last_err: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                out = _manifest.load_step(self.directory, s)
            # MXNetError covers crc/truncation/coverage; a corrupt
            # manifest.json raises ValueError (JSONDecodeError) or
            # KeyError, and an unreadable file raises OSError — all are
            # "this step is damaged", exactly what the walk-back is for
            except (MXNetError, OSError, ValueError, KeyError) as e:
                warnings.warn(
                    f"checkpoint step {s} is unreadable ({e}); falling "
                    f"back to the previous committed step",
                    RuntimeWarning, stacklevel=2)
                self.restore_fallbacks += 1
                last_err = e
                continue
            if s != steps[-1]:
                warnings.warn(
                    f"restored checkpoint step {s} instead of latest "
                    f"step {steps[-1]} — newer step(s) were corrupt",
                    RuntimeWarning, stacklevel=2)
            return out
        raise MXNetError(
            f"every committed checkpoint under {self.directory} "
            f"({steps}) is unreadable; last error: {last_err}"
        ) from last_err

    def close(self):
        """Drain and shut down. Raises a latched background-write error
        rather than swallowing it — a run must not end believing its
        final async snapshot committed when the writer failed."""
        try:
            self.wait()
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=30)
            self.uninstall_preemption_hook()

    # -- preemption ---------------------------------------------------- #
    def install_preemption_hook(self, state_fn: Callable[[], Tuple],
                                exit_after: bool = True):
        """Arm SIGTERM: drain the in-flight snapshot, then write a final
        SYNCHRONOUS one from ``state_fn() -> (step, tree, meta)``.

        With ``exit_after`` the previous SIGTERM disposition is
        re-raised once the final snapshot is committed (so the process
        still dies, but never with work lost since the last commit);
        tests pass ``exit_after=False`` to observe the drain in-process.
        Main-thread only (POSIX signal contract).
        """
        manager = self

        def _handler(signum, frame):
            manager.drain_and_save_final(state_fn)
            if exit_after:
                prev = manager.uninstall_preemption_hook()
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_IGN:
                    pass        # the process had opted to survive TERM
                else:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

        self._sig_prev = signal.signal(signal.SIGTERM, _handler)
        return _handler

    def uninstall_preemption_hook(self):
        prev, self._sig_prev = self._sig_prev, None
        if prev is not None:
            signal.signal(signal.SIGTERM, prev)
        return prev

    def drain_and_save_final(self, state_fn: Callable[[], Tuple]):
        """The preemption sequence, callable directly: drain, then one
        blocking snapshot. Skips cleanly if that step is already on
        disk (e.g. SIGTERM lands right after a periodic save)."""
        self.wait()
        step, tree, meta = state_fn()
        if step in self.all_steps():
            return
        meta = dict(meta or {})
        meta["preempted"] = True
        self.save(int(step), tree, meta=meta, block=True)
