"""SLO-tier serving tests (serve/slo.py + the engine/router tier
machinery — docs/RESILIENCE.md, docs/SERVING.md).

The load-bearing claims: (1) admission, shedding and preemption are
PRIORITY-ordered — LATENCY > STANDARD > BATCH, BATCH drains first
under overload; (2) a preempted request resumes from its emitted
suffix BIT-IDENTICALLY, deadlines stay anchored to the original
admission, and the preemption budget bounds the bouncing with a
retryable PREEMPTED terminal; (3) client cancellation reaches a
CANCELLED terminal from every live state, exactly once, pages
reclaimed; (4) the brownout controller steps degrade levels
deterministically with hysteresis and its effects never retrace a
program; (5) the /metrics rendering round-trips the health
snapshots."""

import re
import time
from types import SimpleNamespace

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Outcome,
                                       Request, Tier, TierPolicy,
                                       build_fleet, render_metrics)
from incubator_mxnet_tpu.serve.chaos import assert_health_consistent
from incubator_mxnet_tpu.serve.slo import (BrownoutController,
                                           default_tier_policies)

VOCAB = 64


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _drain(eng, reqs, max_steps=3000, audit=True):
    steps = 0
    while any(r.outcome is None for r in reqs):
        eng.step()
        if audit:
            eng.audit_pages()
        steps += 1
        assert steps < max_steps, "engine failed to reach quiescence"
    return steps


# ------------------------------------------------------------------- #
# taxonomy / policy units (no engine)
# ------------------------------------------------------------------- #

def test_tier_order_and_policy_defaults():
    assert Tier.LATENCY.order < Tier.STANDARD.order < Tier.BATCH.order
    pols = default_tier_policies()
    assert pols[Tier.LATENCY].can_preempt
    assert not pols[Tier.LATENCY].preemptible
    assert pols[Tier.BATCH].preemptible
    assert not pols[Tier.BATCH].can_preempt
    assert not pols[Tier.STANDARD].preemptible
    # requests coerce string tiers and auto-assign unique ids
    a = Request(np.ones(3, np.int32), tier="BATCH")
    b = Request(np.ones(3, np.int32))
    assert a.tier is Tier.BATCH and b.tier is Tier.STANDARD
    assert a.request_id != b.request_id
    with pytest.raises(MXNetError):
        Request(np.ones(3, np.int32), tier=7)


def test_new_outcomes_taxonomy():
    assert Outcome.PREEMPTED.retryable and not Outcome.PREEMPTED.ok
    assert not Outcome.CANCELLED.retryable and not Outcome.CANCELLED.ok


def test_brownout_controller_hysteresis_unit():
    """Pure-signal unit: the controller steps one level at a time,
    rises only after up_steps consecutive over-threshold updates,
    falls only after down_steps under the exit threshold, and logs
    every transition."""
    bo = BrownoutController(enter=(0.5, 0.7, 0.9), exit_margin=0.2,
                            up_steps=2, down_steps=3)
    snaps = {"num_slots": 4, "queue_depth": 0, "free_pages": 10,
             "active_slots": 0, "estimated_queue_delay_s": None}
    eng = SimpleNamespace(num_pages=11, decode_steps=0,
                          health_snapshot=lambda: dict(snaps))

    def drive(pressure, n):
        # backlog-gated occupancy signal: full queue + occupancy p
        snaps.update(queue_depth=4 * 10, free_pages=10,
                     active_slots=int(4 * pressure))
        levels = []
        for _ in range(n):
            levels.append(bo.update(eng))
            eng.decode_steps += 1
        return levels

    assert drive(1.0, 1) == [0]          # one over-threshold: no move
    assert drive(1.0, 1) == [1]          # second consecutive: L1
    assert drive(1.0, 4) == [1, 2, 2, 3]  # one step per transition
    assert drive(1.0, 3) == [3, 3, 3]    # saturated
    assert drive(0.0, 2) == [3, 3]       # falling needs down_steps
    assert drive(0.0, 1) == [2]
    assert drive(0.0, 3) == [2, 2, 1]
    # a mid-cooldown pressure spike resets the descent counter: the
    # two pre-spike under-threshold updates do not count afterwards
    assert drive(0.0, 2) == [1, 1]
    assert drive(1.0, 1) == [1]
    assert drive(0.0, 3) == [1, 1, 0]
    assert bo.escalations >= 3 and bo.deescalations >= 2
    assert len(bo.timeline) == bo.escalations + bo.deescalations
    for e in bo.timeline:
        assert abs(e["to"] - e["from"]) == 1


def test_brownout_rejects_bad_thresholds():
    with pytest.raises(ValueError):
        BrownoutController(enter=(0.9, 0.7, 0.5))


# ------------------------------------------------------------------- #
# priority admission / shed ordering
# ------------------------------------------------------------------- #

def test_priority_admission_order(model):
    """With one slot held, queued LATENCY is admitted before STANDARD
    before BATCH regardless of submit order (FIFO within a tier)."""
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    rng = np.random.RandomState(0)
    hold = Request(_prompt(rng, 5), max_new_tokens=6)
    eng.submit(hold)
    eng.step()                           # hold occupies the slot
    reqs = []
    for tier in (Tier.BATCH, Tier.STANDARD, Tier.LATENCY,
                 Tier.STANDARD):
        r = Request(_prompt(rng, 5), max_new_tokens=2, tier=tier)
        reqs.append(r)
        eng.submit(r)
    _drain(eng, [hold] + reqs)
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    # one slot serves them strictly one at a time, so completion order
    # IS admission order: LATENCY first, BATCH last, FIFO within
    # STANDARD (the first-submitted STANDARD before the second)
    order = [r.tier for r in sorted(reqs, key=lambda r: r.finish_time)]
    assert order == [Tier.LATENCY, Tier.STANDARD, Tier.STANDARD,
                     Tier.BATCH]
    assert reqs[1].finish_time < reqs[3].finish_time


def test_overload_shed_drains_batch_first(model):
    """A full global queue sheds the lowest queued tier to admit a
    higher one — the displaced BATCH terminal carries a retry hint."""
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_queue=2)
    rng = np.random.RandomState(1)
    hold = Request(_prompt(rng, 5), max_new_tokens=24)
    eng.submit(hold)
    eng.step()
    b1 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    b2 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    assert eng.submit(b1) and eng.submit(b2)
    lat = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    assert eng.submit(lat)               # displaces the NEWEST batch
    assert b2.outcome is Outcome.SHED
    assert b2.retry_after_s is not None and b2.retry_after_s > 0
    assert "displaced" in b2.detail
    assert b1.outcome is None and lat.outcome is None
    # a BATCH newcomer on the still-full queue sheds ITSELF
    b3 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    assert not eng.submit(b3)
    assert b3.outcome is Outcome.SHED
    _drain(eng, [hold, b1, lat])
    assert_health_consistent(eng, [hold, b1, b2, lat, b3])


def test_per_tier_queue_bound_and_default_deadline(model):
    """TierPolicy.max_queue bounds that tier's own share; a tier
    default deadline is applied to deadline-less submissions."""
    eng = InferenceEngine(
        model, num_slots=1, page_size=8, max_len=64,
        tier_policies={Tier.BATCH: TierPolicy(max_queue=1,
                                              preemptible=True),
                       Tier.LATENCY: TierPolicy(
                           can_preempt=True, default_deadline_s=5.0)})
    rng = np.random.RandomState(2)
    hold = Request(_prompt(rng, 5), max_new_tokens=30)
    eng.submit(hold)
    eng.step()
    b1 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    b2 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    assert eng.submit(b1)
    assert not eng.submit(b2)            # tier bound, global unbounded
    assert b2.outcome is Outcome.SHED and "tier depth" in b2.detail
    lat = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    eng.submit(lat)
    assert lat.deadline_s == 5.0 and lat._deadline_abs is not None
    explicit = Request(_prompt(rng, 5), max_new_tokens=2,
                       tier=Tier.LATENCY, deadline_s=9.0)
    eng.submit(explicit)
    assert explicit.deadline_s == 9.0    # explicit beats the default
    eng.shutdown()


# ------------------------------------------------------------------- #
# preemption
# ------------------------------------------------------------------- #

def test_refused_newcomer_does_not_displace_victim(model):
    """A submission the newcomer's OWN tier bound (or delay limit) is
    about to refuse must not shed a lower-tier victim on the way out
    — two terminals where one refusal sufficed."""
    eng = InferenceEngine(
        model, num_slots=1, page_size=8, max_len=64, max_queue=2,
        tier_policies={Tier.LATENCY: TierPolicy(can_preempt=True,
                                                max_queue=1)})
    rng = np.random.RandomState(21)
    hold = Request(_prompt(rng, 5), max_new_tokens=24)
    eng.submit(hold)
    eng.step()
    rb = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    l1 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    assert eng.submit(rb) and eng.submit(l1)   # queue full at 2
    l2 = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    assert not eng.submit(l2)            # LATENCY tier bound refuses it
    assert l2.outcome is Outcome.SHED and "tier depth" in l2.detail
    assert rb.outcome is None            # the BATCH victim survived
    _drain(eng, [hold, rb, l1])


def test_router_cancel_wins_over_requeueable_attempt(model):
    """A cancel racing an attempt terminal that _collect would only
    RE-QUEUE (SHED/PREEMPTED) must win — the request is still live
    from the client's view, and losing the cancel would keep a
    disconnected client's request bouncing through the fleet."""
    rt = build_fleet(model, 1, engine_kw=dict(num_slots=1, page_size=8,
                                              max_len=64))
    rng = np.random.RandomState(22)
    c = Request(_prompt(rng, 5), max_new_tokens=30)
    rt.submit(c)
    for _ in range(30):
        rt.step()
        tr = next((t for t in rt._inflight if t.client is c), None)
        if tr is not None and tr.attempt.token_ids:
            break
    assert tr is not None and tr.attempt.token_ids
    # the replica sheds the attempt underneath the router (drain);
    # before the router collects it, the client cancels
    rt.replicas[0].engine.shutdown("drain")
    att = tr.attempt                     # cancel unwinds tr.attempt
    assert att.outcome is Outcome.SHED
    assert rt.cancel(c)
    assert c.outcome is Outcome.CANCELLED
    assert c.token_ids == att.token_ids  # stream absorbed
    rt.step()                            # _collect must not double-act
    assert c.outcome is Outcome.CANCELLED
    from incubator_mxnet_tpu.serve.chaos import (
        assert_fleet_health_consistent)
    assert_fleet_health_consistent(rt, [c])


def _run_solo(model, req_proto):
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    r = Request(req_proto.prompt_ids.copy(),
                max_new_tokens=req_proto.max_new_tokens,
                tier=req_proto.tier)
    eng.run([r])
    return r


def test_latency_preempts_batch_and_resumes_bit_identically(model):
    rng = np.random.RandomState(3)
    proto = Request(_prompt(rng, 6), max_new_tokens=12,
                    tier=Tier.BATCH)
    base = _run_solo(model, proto)
    assert base.outcome is not None and base.outcome.ok

    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    rb = Request(proto.prompt_ids.copy(), max_new_tokens=12,
                 tier=Tier.BATCH)
    rl = Request(_prompt(rng, 5), max_new_tokens=3, tier=Tier.LATENCY)
    eng.submit(rb)
    for _ in range(4):
        eng.step()
        eng.audit_pages()
    emitted_before = len(rb.token_ids)
    assert 0 < emitted_before < 12
    eng.submit(rl)
    _drain(eng, [rl, rb])
    assert rl.outcome.ok and rb.outcome.ok
    assert rb.preemptions == 1 and eng.preemptions == 1
    # the resumed continuation is bit-identical to the unpreempted run
    assert rb.token_ids == base.token_ids
    # LATENCY finished before the preempted BATCH resumed to the end
    assert rl.finish_time < rb.finish_time
    # preemption state never entered a program
    assert eng.decode_trace_count == 1
    bad = {k: v for k, v in eng.prefill_trace_counts.items() if v != 1}
    assert not bad, f"prefill buckets retraced: {bad}"
    assert_health_consistent(eng, [rb, rl])


def test_standard_neither_preempts_nor_is_preempted(model):
    rng = np.random.RandomState(4)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    rs = Request(_prompt(rng, 5), max_new_tokens=10)
    eng.submit(rs)
    eng.step()
    # LATENCY cannot preempt STANDARD (not preemptible by default)
    rl = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    eng.submit(rl)
    eng.step()
    assert rs.preemptions == 0 and eng.preemptions == 0
    _drain(eng, [rs, rl])
    # STANDARD finished first: it kept its slot
    assert rs.finish_time < rl.finish_time

    eng2 = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    rb = Request(_prompt(rng, 5), max_new_tokens=10, tier=Tier.BATCH)
    eng2.submit(rb)
    eng2.step()
    rs2 = Request(_prompt(rng, 5), max_new_tokens=2)
    eng2.submit(rs2)                     # STANDARD cannot preempt
    eng2.step()
    assert rb.preemptions == 0
    _drain(eng2, [rb, rs2])
    assert rb.finish_time < rs2.finish_time


def test_preemption_budget_bounds_to_preempted_terminal(model):
    """max_preemptions=0: the first preemption is terminal — a
    retryable PREEMPTED with the partial tokens kept and a hint."""
    rng = np.random.RandomState(5)
    proto = Request(_prompt(rng, 6), max_new_tokens=12,
                    tier=Tier.BATCH)
    base = _run_solo(model, proto)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_preemptions=0)
    rb = Request(proto.prompt_ids.copy(), max_new_tokens=12,
                 tier=Tier.BATCH)
    eng.submit(rb)
    for _ in range(4):
        eng.step()
        eng.audit_pages()
    kept = list(rb.token_ids)
    rl = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    eng.submit(rl)
    _drain(eng, [rl, rb])
    assert rb.outcome is Outcome.PREEMPTED
    assert rb.retry_after_s is not None and rb.retry_after_s > 0
    assert rb.token_ids == kept
    assert rb.token_ids == base.token_ids[:len(rb.token_ids)]
    assert rl.outcome.ok
    eng.audit_pages()
    assert_health_consistent(eng, [rb, rl])


def test_preemption_deadline_anchored_to_original_admission(model):
    """Failover-deadline audit (engine half): a preempted request's
    ``_deadline_abs`` must NOT reset when it re-queues — the clock
    keeps running from the ORIGINAL submit."""
    rng = np.random.RandomState(6)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    rb = Request(_prompt(rng, 6), max_new_tokens=12, tier=Tier.BATCH,
                 deadline_s=30.0)
    eng.submit(rb)
    original_abs = rb._deadline_abs
    assert original_abs is not None
    for _ in range(3):
        eng.step()
    rl = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    eng.submit(rl)
    eng.step()                           # the preemption fires here
    assert rb.preemptions == 1
    assert rb._deadline_abs == original_abs
    _drain(eng, [rl, rb])
    assert rb._deadline_abs == original_abs


def test_router_requeue_deadline_anchored_to_original(model):
    """Failover-deadline audit (router half): a replica-death replay
    attempt's deadline is derived from the CLIENT's original
    ``_deadline_abs`` — re-admission must not grant fresh time."""
    rt = build_fleet(model, 2, engine_kw=dict(num_slots=2, page_size=8,
                                              max_len=64))
    rng = np.random.RandomState(7)
    c = Request(_prompt(rng, 6), max_new_tokens=24, deadline_s=60.0)
    rt.submit(c)
    original_abs = c._deadline_abs
    for _ in range(40):
        rt.step()
        if any(t.client is c and t.attempt.token_ids
               for t in rt._inflight):
            break
    tr = next(t for t in rt._inflight if t.client is c)
    rt.replicas[tr.replica].kill("test kill")
    for _ in range(40):
        rt.step()
        live = next((t for t in rt._inflight if t.client is c), None)
        if live is not None and live.attempt is not None:
            break
    assert c.outcome is None and live is not None
    att = live.attempt
    # the attempt's absolute deadline is the client's original one
    # (modulo the microseconds between derivation and submit)
    assert att._deadline_abs is not None
    assert abs(att._deadline_abs - original_abs) < 0.25
    rt.shutdown()


# ------------------------------------------------------------------- #
# cancellation race matrix
# ------------------------------------------------------------------- #

def test_cancel_matrix_engine(model):
    """Cancel while {queued, mid-prefill, mid-decode, mid-spec-verify,
    already-terminal} on the engine: every live state reaches exactly
    one CANCELLED terminal with pages reclaimed; already-terminal is
    refused."""
    rng = np.random.RandomState(8)

    # queued
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    hold = Request(_prompt(rng, 5), max_new_tokens=8)
    eng.submit(hold)
    eng.step()
    q = Request(_prompt(rng, 5), max_new_tokens=4)
    eng.submit(q)
    assert eng.cancel(q)
    assert q.outcome is Outcome.CANCELLED and not q.token_ids
    eng.audit_pages()

    # mid-prefill (chunked: the prompt spans several steps)
    eng2 = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                           chunk_pages=1, token_budget=8)
    pf = Request(_prompt(rng, 30), max_new_tokens=4)
    eng2.submit(pf)
    eng2.step()
    slot = eng2._slots[0]
    assert slot is not None and slot.prefilling
    assert eng2.cancel(pf.request_id)    # by id
    assert pf.outcome is Outcome.CANCELLED
    eng2.audit_pages()
    assert eng2._slots[0] is None

    # mid-decode (partial tokens kept)
    d = Request(_prompt(rng, 5), max_new_tokens=20)
    eng2.submit(d)
    for _ in range(4):
        eng2.step()
    assert len(d.token_ids) > 0 and d.outcome is None
    assert eng2.cancel(d)
    assert d.outcome is Outcome.CANCELLED and d.token_ids
    assert d.retry_after_s is None       # the client asked to stop
    eng2.audit_pages()

    # mid-spec-verify (a live speculative slot between verify steps)
    eng3 = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                           spec_k=2, spec_patience=0)
    sv = Request(_prompt(rng, 6), max_new_tokens=24)
    eng3.submit(sv)
    for _ in range(6):
        eng3.step()
        if eng3.spec_steps > 0 and eng3._slots[0] is not None:
            break
    assert eng3.spec_steps > 0 and eng3._slots[0] is not None
    assert eng3.cancel(sv)
    assert sv.outcome is Outcome.CANCELLED
    eng3.audit_pages()

    # already-terminal: refused (the double-finish guard's contract)
    assert not eng2.cancel(d)
    assert not eng2.cancel(d.request_id)
    assert d.outcome is Outcome.CANCELLED
    # unknown id: refused
    assert not eng2.cancel(10 ** 9)
    _drain(eng, [hold], audit=True)
    assert_health_consistent(eng2, [pf, d])


def test_cancel_matrix_router(model):
    """Cancel while {queued, in-flight} through the router; an
    already-terminal client is refused; partial tokens kept."""
    rt = build_fleet(model, 2, engine_kw=dict(num_slots=1, page_size=8,
                                              max_len=64),
                     replica_queue_depth=0)
    rng = np.random.RandomState(9)
    a = Request(_prompt(rng, 5), max_new_tokens=30)
    b = Request(_prompt(rng, 5), max_new_tokens=30)
    c = Request(_prompt(rng, 5), max_new_tokens=30)
    for r in (a, b, c):
        rt.submit(r)
    # c is queued behind the two slots' worth of work
    while not any(t.client is c for t in rt._queue):
        rt.step()
    assert rt.cancel(c)
    assert c.outcome is Outcome.CANCELLED and not c.token_ids
    # a is in flight: cancel reclaims the engine attempt too (tokens
    # live on the ATTEMPT until absorbed — watch those, not a's)
    for _ in range(30):
        rt.step()
        tr = next((t for t in rt._inflight if t.client is a), None)
        if tr is not None and tr.attempt.token_ids:
            break
    assert a.outcome is None and tr.attempt.token_ids
    assert rt.cancel(a.request_id)
    assert a.outcome is Outcome.CANCELLED and a.token_ids
    for rep in rt.replicas:
        rep.engine.audit_pages()
    # refused on the already-terminal client
    assert not rt.cancel(a) and not rt.cancel(c)
    rt.run([])                           # drain b
    assert b.outcome is not None and b.outcome.ok
    from incubator_mxnet_tpu.serve.chaos import (
        assert_fleet_health_consistent)
    assert_fleet_health_consistent(rt, [a, b, c])


# ------------------------------------------------------------------- #
# brownout effects on the engine (forced levels — no retrace, ever)
# ------------------------------------------------------------------- #

class _FixedBrownout:
    """A controller stub pinned at one level: isolates the engine's
    level EFFECTS from the controller's signal dynamics."""

    def __init__(self, level):
        self.level = level
        self.escalations = 0
        self.deescalations = 0
        self.timeline = []

    def update(self, engine):
        return self.level


def test_brownout_level1_disables_speculation(model):
    rng = np.random.RandomState(10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          spec_k=3, spec_patience=0,
                          brownout=_FixedBrownout(1))
    reqs = [Request(_prompt(rng, 6), max_new_tokens=8)
            for _ in range(3)]
    for r in reqs:
        eng.submit(r)
    _drain(eng, reqs)
    assert eng.drafted_tokens == 0 and eng.spec_steps == 0
    assert eng.verify_trace_count == 0   # the wide program never ran
    assert eng.decode_trace_count == 1


def test_brownout_level2_clamps_prefill_budget(model):
    rng = np.random.RandomState(11)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          chunk_pages=1, token_budget=32,
                          brownout=_FixedBrownout(2))
    reqs = [Request(_prompt(rng, 30), max_new_tokens=2)
            for _ in range(2)]
    for r in reqs:
        eng.submit(r)
    _drain(eng, reqs)
    assert eng.max_step_prefill_tokens <= 8   # one chunk, not 32
    bad = {k: v for k, v in eng.prefill_trace_counts.items() if v != 1}
    assert not bad                        # same buckets, no retrace


def test_brownout_level3_clamps_batch_admissions(model):
    rng = np.random.RandomState(12)
    bo = _FixedBrownout(3)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          brownout=bo)
    rb = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    rs = Request(_prompt(rng, 5), max_new_tokens=2)
    eng.submit(rb)
    eng.submit(rs)
    for _ in range(60):
        eng.step()
    # STANDARD ran to completion; BATCH never left the queue
    assert rs.outcome is not None and rs.outcome.ok
    assert rb.outcome is None and len(eng._queue) == 1
    bo.level = 0                         # pressure clears
    _drain(eng, [rb])
    assert rb.outcome.ok


def test_brownout_closed_loop_escalates_and_recovers(model):
    """End-to-end: a backlog storm drives the real controller up the
    ladder; draining brings it back to level 0; transitions are
    logged; nothing retraced."""
    rng = np.random.RandomState(13)
    bo = BrownoutController(up_steps=1, down_steps=2, delay_ref=0.05)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          num_pages=1 + 2 * 8, chunk_pages=1,
                          brownout=bo, spec_k=2)
    reqs = [Request(_prompt(rng, 12), max_new_tokens=8,
                    tier=[Tier.LATENCY, Tier.STANDARD,
                          Tier.BATCH][i % 3]) for i in range(9)]
    eng.run(reqs)
    assert all(r.outcome is not None for r in reqs)
    assert bo.escalations >= 1 and bo.deescalations >= 1
    assert bo.level == 0
    assert len(bo.timeline) == bo.escalations + bo.deescalations
    assert eng.decode_trace_count <= 1 and eng.verify_trace_count <= 1
    snap = eng.health_snapshot()
    assert snap["brownout_level"] == 0
    assert snap["brownout_escalations"] == bo.escalations
    eng.audit_pages()


def test_brownout_clamp_cannot_sustain_itself(model):
    """Deadlock regression: a BATCH-only backlog on an otherwise idle
    engine must NOT hold the controller at level 3 — the delay signal
    is scoped to the priority tiers, so the clamped BATCH queue
    cannot sustain the clamp that parked it. (Found end-to-end: the
    first requests' compile-dominated EWMA pushed the estimate over
    every threshold, level 3 clamped BATCH, and the queued BATCH kept
    the BATCH-inclusive estimate high forever — the stall watchdog,
    not the controller, had to break the wedge.)"""
    rng = np.random.RandomState(20)
    bo = BrownoutController(up_steps=1, down_steps=2, delay_ref=0.01)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          brownout=bo)
    # calibrate a HUGE ewma (the compile-step effect, distilled)
    eng._ewma_service_s = 50.0
    bo.level = 3
    rb = [Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
          for _ in range(4)]
    for r in rb:
        eng.submit(r)
    for _ in range(200):
        eng.step()
        if all(r.outcome is not None for r in rb):
            break
    assert all(r.outcome is not None and r.outcome.ok for r in rb), \
        [str(r.outcome) for r in rb]
    for _ in range(3 * bo.down_steps):   # idle evaluations: step down
        eng.step()
    assert bo.level == 0
    eng.audit_pages()


# ------------------------------------------------------------------- #
# /metrics rendering (serve/metrics.py)
# ------------------------------------------------------------------- #

_SAMPLE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)"
                     r"(\{[^}]*\})?\s([-+0-9.eE]+)$")


def _golden_parse(text):
    """Prometheus text-format validation: every sample line parses and
    its metric name was declared by a preceding # TYPE line. A
    histogram declaration for ``x`` covers the convention-suffixed
    samples ``x_bucket`` / ``x_sum`` / ``x_count`` (the round-17
    latency histograms, serve/metrics.py)."""
    typed = {}
    samples = []
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            assert name not in typed, f"duplicate TYPE for {name}"
            typed[name] = mtype
            continue
        m = _SAMPLE.match(line)
        assert m, f"unparseable metrics line: {line!r}"
        name, labels, value = m.groups()
        if name not in typed:
            base = name.rsplit("_", 1)[0]
            assert name.rsplit("_", 1)[-1] in ("bucket", "sum",
                                               "count") and \
                typed.get(base) == "histogram", \
                f"sample before TYPE: {line!r}"
        samples.append((name, labels or "", float(value)))
    return typed, samples


def test_metrics_engine_golden(model):
    rng = np.random.RandomState(14)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          max_queue=2, brownout=True)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=3,
                    tier=[Tier.LATENCY, Tier.BATCH][i % 2])
            for i in range(5)]
    for r in reqs:
        eng.submit(r)
    _drain(eng, reqs)
    snap = eng.health_snapshot()
    typed, samples = _golden_parse(render_metrics(snap))
    by = {}
    for name, labels, v in samples:
        by.setdefault(name, {})[labels] = v
    total = sum(v for v in by["mxtpu_serve_requests_total"].values())
    assert total == sum(snap["outcomes"].values()) == len(reqs)
    tier_total = sum(
        v for v in by["mxtpu_serve_tier_requests_total"].values())
    assert tier_total == total
    assert typed["mxtpu_serve_requests_total"] == "counter"
    assert typed["mxtpu_serve_queue_depth"] == "gauge"
    assert by["mxtpu_serve_queue_depth"][""] == snap["queue_depth"]
    assert by["mxtpu_serve_free_pages"][""] == snap["free_pages"]
    assert by["mxtpu_serve_brownout_level"][""] == \
        snap["brownout_level"]
    assert by["mxtpu_serve_decode_steps_total"][""] == \
        snap["decode_steps"]
    # per-tier series carry both labels
    for labels in by["mxtpu_serve_tier_requests_total"]:
        assert "tier=" in labels and "outcome=" in labels
    # KV-pool capacity surface (quantized serving): bytes + page
    # gauges parse back to the snapshot, and the info gauge carries
    # the payload dtype/quant mode as labels
    assert by["mxtpu_serve_kv_pool_bytes"][""] == snap["kv_pool_bytes"]
    assert snap["kv_pool_bytes"] > 0
    assert by["mxtpu_serve_kv_quantized_pages"][""] == \
        snap["kv_quantized_pages"] == 0          # unquantized engine
    (info_labels, info_v), = by["mxtpu_serve_kv_pool_info"].items()
    assert info_v == 1.0
    assert 'dtype="float32"' in info_labels
    assert 'quant="off"' in info_labels


def test_metrics_engine_golden_quantized(model):
    """The int8 arm of the capacity surface: the info gauge flips its
    labels, live pages count as quantized pages, and the pool-bytes
    gauge shrinks ~4x against the f32 twin at identical geometry."""
    rng = np.random.RandomState(16)
    engines = {q: InferenceEngine(model, num_slots=2, page_size=8,
                                  max_len=64, kv_quant=q)
               for q in (None, "int8")}
    snaps = {}
    for q, eng in engines.items():
        reqs = [Request(_prompt(rng, 5), max_new_tokens=3)
                for _ in range(2)]
        for r in reqs:
            eng.submit(r)
        _drain(eng, reqs)
        snaps[q] = eng.health_snapshot()
    snap = snaps["int8"]
    typed, samples = _golden_parse(render_metrics(snap))
    by = {}
    for name, labels, v in samples:
        by.setdefault(name, {})[labels] = v
    assert by["mxtpu_serve_kv_pool_bytes"][""] == snap["kv_pool_bytes"]
    assert by["mxtpu_serve_kv_quantized_pages"][""] == \
        snap["kv_quantized_pages"]
    # the prefix index retains the prompts' full pages → live pages
    assert snap["kv_quantized_pages"] == \
        engines["int8"].num_pages - 1 - snap["free_pages"]
    (info_labels, info_v), = by["mxtpu_serve_kv_pool_info"].items()
    assert 'dtype="int8"' in info_labels and 'quant="int8"' in info_labels
    ratio = snaps[None]["kv_pool_bytes"] / snap["kv_pool_bytes"]
    assert ratio > 3.0                   # f32 → int8 + scale metadata


def test_metrics_engine_golden_hier_tiers(model, tmp_path):
    """The hierarchical-cache surface: ``kv_tier_bytes{tier=...}``
    gauges plus the demote/promote/hit/fallback counter family parse
    back to the snapshot of an engine whose tiers actually cycled."""
    rng = np.random.RandomState(17)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=7, prefix_cache=True,
                          kv_tiers={"dram_bytes": 128 << 10,
                                    "disk_dir": str(tmp_path)})
    head = _prompt(rng, 24)
    heads = [head] + [_prompt(rng, 24) for _ in range(4)]
    for p in [0, 1, 2, 0, 1, 2, 3, 4, 0]:
        reqs = [Request(np.concatenate([heads[p], _prompt(rng, 5)]),
                        max_new_tokens=3)]
        eng.submit(reqs[0])
        _drain(eng, reqs)
    assert eng.tier_demotions > 0 and eng.tier_promotions > 0
    snap = eng.health_snapshot()
    typed, samples = _golden_parse(render_metrics(snap))
    by = {}
    for name, labels, v in samples:
        by.setdefault(name, {})[labels] = v
    assert typed["mxtpu_serve_kv_tier_bytes"] == "gauge"
    for tier in ("dram", "disk"):
        assert by["mxtpu_serve_kv_tier_bytes"][f'{{tier="{tier}"}}'] \
            == snap["kv_tier_bytes"][tier]
    for key, metric in (
            ("tier_demotions", "kv_tier_demotions_total"),
            ("tier_disk_demotions", "kv_tier_disk_demotions_total"),
            ("tier_promotions", "kv_tier_promotions_total"),
            ("tier_hits", "kv_tier_hits_total"),
            ("tier_hit_tokens", "kv_tier_hit_tokens_total"),
            ("tier_misses", "kv_tier_misses_total"),
            ("tier_crc_fallbacks", "kv_tier_crc_fallbacks_total"),
            ("tier_disk_errors", "kv_tier_disk_errors_total"),
            ("tier_dropped", "kv_tier_dropped_total")):
        assert typed[f"mxtpu_serve_{metric}"] == "counter"
        assert by[f"mxtpu_serve_{metric}"][""] == snap[key], metric
    eng.audit_pages()


def test_metrics_router_golden(model):
    rt = build_fleet(model, 2, engine_kw=dict(num_slots=1, page_size=8,
                                              max_len=64))
    rng = np.random.RandomState(15)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=3)
            for _ in range(3)]
    rt.run(reqs)
    snap = rt.health_snapshot()
    typed, samples = _golden_parse(render_metrics(snap))
    by = {}
    for name, labels, v in samples:
        by.setdefault(name, {})[labels] = v
    # fleet-level counters count CLIENT requests only — per-replica
    # attempt counters live in their own _replica_* namespace
    assert sum(by["mxtpu_serve_requests_total"].values()) == len(reqs)
    assert sum(by["mxtpu_serve_replica_requests_total"].values()) >= \
        len(reqs)
    ups = by["mxtpu_serve_replica_up"]
    assert set(ups) == {'{replica="0"}', '{replica="1"}'}
    assert all(v == 1.0 for v in ups.values())
    # per-replica engine gauges are labelled
    assert '{replica="0"}' in by["mxtpu_serve_replica_free_pages"]
    # None-valued gauges are skipped, not rendered as NaN
    assert "NaN" not in render_metrics(snap)


# ------------------------------------------------------------------- #
# fleet-level tier flow
# ------------------------------------------------------------------- #

def test_router_preempted_attempt_requeues_and_resumes(model):
    """An engine that exhausts its preemption budget hands the router
    a retryable PREEMPTED attempt — the router must re-queue it like a
    shed (resume-from-suffix), not propagate the failure."""
    rt = build_fleet(
        model, 1,
        engine_kw=dict(num_slots=1, page_size=8, max_len=64,
                       max_preemptions=0),
        max_requeues=3)
    rng = np.random.RandomState(16)
    base = _run_solo(model, Request(_prompt(rng, 6), max_new_tokens=10,
                                    tier=Tier.BATCH))
    rb = Request(base.prompt_ids.copy(), max_new_tokens=10,
                 tier=Tier.BATCH, seed=0)
    rt.submit(rb)
    for _ in range(60):
        rt.step()
        tr = next((t for t in rt._inflight if t.client is rb), None)
        if tr is not None and tr.attempt.token_ids:
            break
    assert tr is not None and tr.attempt.token_ids
    lat = Request(_prompt(rng, 5), max_new_tokens=2,
                  tier=Tier.LATENCY)
    rt.submit(lat)
    rt.run([])
    assert lat.outcome is not None and lat.outcome.ok
    assert rb.outcome is not None and rb.outcome.ok
    assert rb.token_ids == base.token_ids  # resumed bit-identically
    assert rt.requeues >= 1
    for rep in rt.replicas:
        rep.engine.audit_pages()


def test_router_tier_priority_dispatch_and_by_tier_health(model):
    rt = build_fleet(model, 1, engine_kw=dict(num_slots=1, page_size=8,
                                              max_len=64),
                     replica_queue_depth=0)
    rng = np.random.RandomState(17)
    hold = Request(_prompt(rng, 5), max_new_tokens=10)
    rt.submit(hold)
    for _ in range(20):
        rt.step()
        if hold.token_ids:
            break
    rb = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.BATCH)
    rl = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    rt.submit(rb)                        # BATCH queued first...
    rt.submit(rl)
    rt.run([])
    assert rl.finish_time < rb.finish_time  # ...LATENCY served first
    snap = rt.health_snapshot()
    assert snap["outcomes_by_tier"]["LATENCY"]["MAX_TOKENS"] == 1
    assert snap["outcomes_by_tier"]["BATCH"]["MAX_TOKENS"] == 1
    from incubator_mxnet_tpu.serve.chaos import (
        assert_fleet_health_consistent)
    assert_fleet_health_consistent(rt, [hold, rb, rl])
