"""Global random state.

The reference keeps *stateful* per-device RNG (`mshadow::Random`,
`src/resource.cc` kParallelRandom; file-level citation — SURVEY.md caveat).
JAX RNG is counter-based and functional. We bridge the two contracts with a
process-global splittable key stream (SURVEY.md §7.2 "RNG parity"):

  - ``mx.random.seed(n)`` resets the stream deterministically.
  - every stochastic op pulls a fresh subkey via ``new_key()`` — sampling the
    same op twice gives different draws (stateful illusion), while seeding
    replays the exact sequence (reproducibility contract).
  - traced code (hybridized blocks, jitted train steps) must take keys as
    *inputs*; ``new_key()`` returns a concrete array suitable for feeding.
"""

from __future__ import annotations

import threading

import jax
import numpy as _np

__all__ = ["seed", "new_key", "get_state", "set_state"]

# Random bits must not depend on how the consuming array is sharded over
# the mesh: with the legacy (non-partitionable) threefry lowering, the
# same dropout mask computed on a dp2×sp4 vs a dp8 mesh comes out
# DIFFERENT, so data-parallel and model-parallel runs of the same seed
# silently diverge. Newer jax defaults this on; force it on the older
# jax this container ships so RNG is layout-invariant everywhere.
try:
    jax.config.update("jax_threefry_partitionable", True)
except Exception:  # very old jax without the flag: keep legacy behavior
    pass

_state = threading.local()
_DEFAULT_SEED = 0


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


def seed(seed_state: int, ctx=None):  # ctx accepted for reference parity
    """Seed the global RNG stream (parity: `mx.random.seed`,
    `python/mxnet/random.py`)."""
    _state.key = jax.random.PRNGKey(int(seed_state) & 0x7FFFFFFF)


class _KeyProvider:
    """Trace-scoped key source: inside a traced (hybridized/jitted) region
    the base key is a traced INPUT, so replays draw fresh randomness instead
    of baking one mask into the compiled program."""

    def __init__(self, base):
        self._cur = base

    def __call__(self):
        self._cur, sub = jax.random.split(self._cur)
        return sub


class key_provider:
    """Context manager installing a trace-scoped key provider."""

    def __init__(self, base):
        self._provider = _KeyProvider(base)

    def __enter__(self):
        self._prev = getattr(_state, "provider", None)
        _state.provider = self._provider
        return self._provider

    def __exit__(self, *exc):
        _state.provider = self._prev


def new_key() -> "jax.Array":
    """Split one subkey off the global stream (advances the stream).
    Under an active key_provider (hybridize trace), draws from the traced
    key instead."""
    provider = getattr(_state, "provider", None)
    if provider is not None:
        return provider()
    key = _ensure()
    _state.key, sub = jax.random.split(key)
    return sub


def new_keys(n: int):
    key = _ensure()
    keys = jax.random.split(key, n + 1)
    _state.key = keys[0]
    return keys[1:]


def get_state():
    return _ensure()


def set_state(key):
    _state.key = key


def np_rng() -> _np.random.RandomState:
    """A host-side numpy RNG derived from the stream (for shuffling etc.)."""
    sub = new_key()
    return _np.random.RandomState(int(jax.device_get(sub)[0]) & 0x7FFFFFFF)
