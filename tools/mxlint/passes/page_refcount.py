"""Pass 3 — KV page refcount pairing in serve/.

``PageAllocator`` invariants (serve/paged_kv.py): every ``alloc``/
``incref`` is someone's RESPONSIBILITY to ``decref``/``free``; page 0
is the null page and is never allocated, shared, or freed; the
refcount array is the allocator's alone. A leaked reference never
crashes — it silently shrinks the pool until admission starves, which
is exactly why it needs a static pass (the runtime page audit only
sees leaks on paths a test drives).

Checks:
  - an ``alloc``/``incref`` call whose enclosing scope (function, then
    class, then module) contains no reachable ``decref``/``free``/
    ``release_held`` — an acquire with no paired release anywhere in
    the owning component;
  - literal page 0 (or ``NULL_PAGE``) passed to ``alloc``-family calls;
  - refcount internals (``._rc`` / ``._free``) touched outside
    ``PageAllocator``;
  - tier-store internals (``._entries`` / ``._dram_used`` /
    ``._disk_used``) touched outside ``KVTierStore`` — demoted-page
    bookkeeping belongs to the store (readers go through
    ``entries()`` / ``tier_bytes()``);
  - allocator-mutation calls lexically inside ``KVTierStore`` — a
    demoted page has NO page number and no refcount (free XOR live
    XOR demoted); a tier store that allocs or frees HBM pages is
    conflating the tiers, and freeing a "demoted page" corrupts the
    free list;
  - transport internals (``._records`` / ``._chain_crc``) touched
    outside ``PageCapsule``/``PageTransport`` (serve/transport.py) —
    the capsule's payload records and crc chain are what ``verify()``
    vouches for; outside writes could forge a chain the destination
    would trust (consumers go through ``verify()``/``payloads()``/
    ``nbytes``, fault injection through the public ``corrupt()``
    seam);
  - in-capsule custody (``._capsule_pages``) touched outside
    ``InferenceEngine`` — a detached slot's pages are the fourth
    page state (free XOR live XOR demoted XOR in-capsule) and only
    the engine's ``detach_slot``/``release_capsule`` may move pages
    across that boundary, or ``audit_pages`` stops meaning anything.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import (Finding, Project, dotted, enclosing_scopes,
                    qualname_of)

RULE = "page-refcount"

_SCOPE = "incubator_mxnet_tpu/serve/"
_ACQUIRE = {"alloc", "incref"}
_RELEASE = {"decref", "free", "release_held"}
_INTERNAL = {"_rc", "_free"}
_TIER_INTERNAL = {"_entries", "_dram_used", "_disk_used"}
_TRANSPORT_INTERNAL = {"_records", "_chain_crc"}
_CUSTODY_INTERNAL = {"_capsule_pages"}
_ALLOC_MUTATORS = _ACQUIRE | _RELEASE


def _calls_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute):
            yield sub


def _has_release(scope: ast.AST) -> bool:
    return any(c.func.attr in _RELEASE for c in _calls_in(scope))


def _null_page_arg(call: ast.Call) -> bool:
    if not call.args:
        return False
    a = call.args[0]
    if isinstance(a, ast.Constant) and a.value == 0:
        return True
    return isinstance(a, ast.Name) and a.id == "NULL_PAGE"


class PageRefcountPass:
    name = "page-refcount"
    rules = (RULE,)

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for unit in project.units:
            if unit.tree is None or not unit.path.startswith(_SCOPE):
                continue
            for node in ast.walk(unit.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute):
                    attr = node.func.attr
                    if attr in _ACQUIRE | {"decref"} | {"free"} \
                            and _null_page_arg(node):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"literal null page passed to "
                            f"`.{attr}()` — page 0 is never "
                            f"allocated, shared, or freed",
                            symbol=qualname_of(node)))
                    if attr in _ACQUIRE:
                        f = self._check_pairing(node, unit)
                        if f is not None:
                            out.append(f)
                    if attr in _ALLOC_MUTATORS \
                            and self._inside(node, "KVTierStore"):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"`.{attr}()` inside KVTierStore — a "
                            f"demoted page has no page number and no "
                            f"refcount (free XOR live XOR demoted); "
                            f"the tier store must never touch the "
                            f"HBM allocator",
                            symbol=qualname_of(node)))
                elif isinstance(node, ast.Attribute) \
                        and node.attr in _INTERNAL \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    if not self._inside_allocator(node):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"refcount internals `self.{node.attr}` "
                            f"touched outside PageAllocator — refcount "
                            f"arithmetic belongs to the allocator",
                            symbol=qualname_of(node)))
                elif isinstance(node, ast.Attribute) \
                        and node.attr in _TIER_INTERNAL:
                    if not self._inside(node, "KVTierStore"):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"tier-store internals `.{node.attr}` "
                            f"touched outside KVTierStore — demoted-"
                            f"page bookkeeping belongs to the store "
                            f"(read via entries()/tier_bytes())",
                            symbol=qualname_of(node)))
                elif isinstance(node, ast.Attribute) \
                        and node.attr in _TRANSPORT_INTERNAL:
                    if not (self._inside(node, "PageCapsule") or
                            self._inside(node, "PageTransport")):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"transport internals `.{node.attr}` "
                            f"touched outside PageCapsule/"
                            f"PageTransport — an outside write could "
                            f"forge the crc chain verify() vouches "
                            f"for (read via verify()/payloads()/"
                            f"nbytes; inject faults via corrupt())",
                            symbol=qualname_of(node)))
                elif isinstance(node, ast.Attribute) \
                        and node.attr in _CUSTODY_INTERNAL:
                    if not self._inside(node, "InferenceEngine"):
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"in-capsule custody `.{node.attr}` "
                            f"touched outside InferenceEngine — only "
                            f"detach_slot/release_capsule may move "
                            f"pages across the in-capsule page state "
                            f"(free XOR live XOR demoted XOR "
                            f"in-capsule)",
                            symbol=qualname_of(node)))
        return out

    @staticmethod
    def _inside(node: ast.AST, cls: str) -> bool:
        return any(isinstance(s, ast.ClassDef) and s.name == cls
                   for s in enclosing_scopes(node))

    @classmethod
    def _inside_allocator(cls, node: ast.AST) -> bool:
        return cls._inside(node, "PageAllocator")

    def _check_pairing(self, call: ast.Call,
                       unit) -> Optional[Finding]:
        # skip calls ON the allocator itself (its own bookkeeping)
        if self._inside_allocator(call):
            return None
        scopes = enclosing_scopes(call)
        for scope in scopes:                # function(s), then class
            if _has_release(scope):
                return None
        if unit.tree is not None and _has_release(unit.tree):
            return None                     # module-level pairing
        d = dotted(call.func) or call.func.attr
        return Finding(
            RULE, unit.path, call.lineno,
            f"`{d}()` acquires a page reference but no "
            f"decref/free/release_held is reachable in the enclosing "
            f"function, class, or module — a silent pool leak",
            symbol=qualname_of(call))
