"""Elastic-fleet tests (serve/router.py membership +
serve/fleet_supervisor.py policy).

The load-bearing claims: (1) ``add_replica`` admits a cold engine
through WARMING — spill-only until it graduates, compile steps exempt
from the heartbeat AND from warmup evidence — and ``remove_replica``
drains via slot migration with resume-from-suffix replay as the
always-correct fallback: zero lost requests, exactly one terminal,
clean page audits on every survivor; (2) membership is TOMBSTONED —
replica index == list position survives every add/remove/upgrade, so
mid-dispatch removal can neither skew spill selection nor raise on a
stale index; (3) refusals are LOUD: double remove, removing the last
live replica, and upgrading without a weight source all raise typed
errors; (4) every shed emitted during a membership transition carries
an honest ``retry_after_s`` and the PR-15 frontend surfaces it as
Retry-After over one stable endpoint while the fleet churns; (5) the
FleetSupervisor's policy (grow on sustained pressure, shrink on
sustained idleness, replace deaths from the latest checkpoint, roll
upgrades one replica at a time and halt while degraded) is pure
snapshot-driven hysteresis — unit-tested against a fake router,
integration-tested on a live fleet; (6) the race matrix
(add-during-drain, remove-during-kill-failover,
cancel-vs-migrate-vs-retire) resolves to the standard outcome
taxonomy with no wedge and no double-finish.

The race matrix and supervisor integration runs each build + compile
fleets (~10-20s each), so they ride in ``slow`` (ci stage_unit runs
them; the elasticsmoke CI stage ALSO churns membership end-to-end on
every run) — tier-1 keeps the host-only policy/refusal units plus the
cheap single-fleet regressions."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (FleetSupervisor, InferenceEngine,
                                       Outcome, Request, ReplicaState,
                                       build_fleet, render_metrics)
from incubator_mxnet_tpu.serve.chaos import (DrainKill, KillReplica,
                                             ScaleDownRace,
                                             SupervisorChaos,
                                             assert_fleet_health_consistent,
                                             run_fleet_chaos)
from incubator_mxnet_tpu.serve.events import EventType

VOCAB = 64


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


ENG_KW = dict(num_slots=2, page_size=8, max_len=64, chunk_pages=1,
              prefix_cache=True)


def _fleet(model, n=2, **router_kw):
    router_kw.setdefault("seed", 3)
    return build_fleet(model, n, engine_kw=dict(ENG_KW), **router_kw)


def _engine(model, **kw):
    return InferenceEngine(model, **dict(ENG_KW, **kw))


def _workload(n, seed=42):
    """Greedy (parity-assertable): persona-shared + unique ragged."""
    rng = np.random.RandomState(seed)
    persona = rng.randint(0, VOCAB, size=(14,)).astype(np.int32)
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            prompt = np.concatenate(
                [persona, rng.randint(0, VOCAB,
                                      size=(3 + i % 4,)).astype(np.int32)])
        else:
            prompt = rng.randint(0, VOCAB,
                                 size=(5 + 3 * (i % 3),)).astype(np.int32)
        reqs.append(Request(prompt, max_new_tokens=8 + 2 * (i % 3)))
    return reqs


_BASELINES = {}


def _baseline(model, n):
    key = n
    if key not in _BASELINES:
        rt = _fleet(model)
        reqs = _workload(n)
        rt.run(reqs)        # plain run: the oracle needs streams, not
        assert all(r.outcome is not None and r.outcome.ok for r in reqs)
        _BASELINES[key] = [list(r.token_ids) for r in reqs]
    return _BASELINES[key]  # the per-step audit run_fleet_chaos does


def _same_params(router):
    """The serving weights as a warm_start source — the same-weights
    upgrade whose survivor streams must stay bit-identical."""
    live = next(r for r in router.replicas
                if r.state not in (ReplicaState.DEAD,
                                   ReplicaState.RETIRED))
    return {str(i): p.data().asnumpy()
            for i, p in enumerate(live.engine._eng_params)}


# --------------------------------------------------------------------- #
# membership mechanics: refusal ladder (host-only, no engine stepping)
# --------------------------------------------------------------------- #

def test_membership_refusals_are_loud(model):
    rt = _fleet(model, n=2)
    # out of range
    with pytest.raises(MXNetError, match="no replica"):
        rt.remove_replica(7)
    # upgrade needs a weight source
    with pytest.raises(MXNetError, match="weight source"):
        rt.upgrade_replica(0)
    # drain replica 1, then a second remove must be refused LOUDLY
    rt.remove_replica(1)
    assert rt.replicas[1].state is ReplicaState.DRAINING
    with pytest.raises(MXNetError, match="double membership"):
        rt.remove_replica(1)
    with pytest.raises(MXNetError, match="double membership"):
        rt.upgrade_replica(1, params=_same_params(rt))
    # removing the only non-draining replica would zero the fleet
    with pytest.raises(MXNetError, match="last live replica"):
        rt.remove_replica(0)
    # bad role on admission
    with pytest.raises(MXNetError, match="role"):
        rt.add_replica(_engine(model), role="nonsense")
    # a retired tombstone stays refused
    rt.step()                            # finalises the idle drain
    assert rt.replicas[1].state is ReplicaState.RETIRED
    with pytest.raises(MXNetError, match="nothing to drain"):
        rt.remove_replica(1)
    # the tally and events agree
    snap = rt.health_snapshot()
    assert snap["fleet_size"] == 1 and snap["scale_downs"] == 1
    etypes = [e.etype for e in rt.flight.events()]
    assert EventType.SCALE_DOWN in etypes


def test_add_replica_enters_warming_and_graduates(model):
    rt = _fleet(model, n=1, warmup_steps=2)
    idx = rt.add_replica(_engine(model))
    assert idx == 1
    rep = rt.replicas[idx]
    assert rep.state is ReplicaState.WARMING
    assert rep.engine._component == "replica1"
    # warming replicas are routable (spill) but NOT affinity targets
    assert rep in rt._routable() and rep not in rt._serving()
    # idle healthy steps are warmup evidence — after warmup_steps the
    # replica graduates and the WARMUP/SCALE_UP events are on the
    # timeline
    for _ in range(3):
        rt.step()
    assert rep.state is ReplicaState.SERVING
    evs = [(e.etype, e.data.get("phase")) for e in rt.flight.events()]
    assert (EventType.SCALE_UP, None) in evs
    assert (EventType.WARMUP, "start") in evs
    assert (EventType.WARMUP, "done") in evs
    snap = rt.health_snapshot()
    assert snap["fleet_size"] == 2 and snap["scale_ups"] == 1


def test_metrics_render_fleet_size_and_replica_states(model):
    rt = _fleet(model, n=2)
    rt.add_replica(_engine(model))       # WARMING
    rt.remove_replica(1)                 # DRAINING
    text = render_metrics(rt.health_snapshot())
    assert "mxtpu_serve_fleet_size 3" in text     # all three alive
    assert "mxtpu_serve_scale_ups_total 1" in text
    assert "mxtpu_serve_scale_downs_total 0" in text
    assert "mxtpu_serve_upgrades_total 0" in text
    up = "mxtpu_serve_replica_up"
    assert up + '{replica="1"} 0.25' in text      # DRAINING
    assert up + '{replica="2"} 0.75' in text      # WARMING
    # golden-parse: every line is "name{labels} value" or a comment
    for line in text.splitlines():
        assert line.startswith("#") or len(line.rsplit(" ", 1)) == 2


# --------------------------------------------------------------------- #
# membership-change-safe routing (the stale-index regression)
# --------------------------------------------------------------------- #

@pytest.mark.slow    # live decode on 2 fleets (~13s of shape-bucket
def test_remove_replica_mid_dispatch_zero_loss_and_parity(model):
    # compiles) and tier-1 sits at the 870s wall; ci stage_unit runs
    # it every time and chaos_bench --elastic scale_down_race re-gates
    # the same remove-mid-flight invariant in elasticsmoke
    """The satellite regression: a replica removed BETWEEN dispatch
    passes (stale indices in flight, round-robin cursor mid-sequence)
    must neither raise nor lose a request — and the survivors' token
    streams stay bit-identical to a fixed-fleet run."""
    base = _baseline(model, 6)
    rt = _fleet(model, n=3, affinity=False)   # round-robin: the
    reqs = _workload(6)                       # cursor-skew surface
    for r in reqs:
        rt.submit(r)
    rt.step()                            # in-flight on all replicas
    rt.remove_replica(2)
    guard = 0
    while any(r.outcome is None for r in reqs):
        rt.step()
        guard += 1
        assert guard < 3000, "fleet wedged after mid-dispatch removal"
    for _ in range(4):                   # let the drain finalise
        rt.step()
    assert rt.replicas[2].state is ReplicaState.RETIRED
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    for i, r in enumerate(reqs):
        assert list(r.token_ids) == base[i], f"request {i} diverged"
    assert_fleet_health_consistent(rt, reqs)
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD:
            rep.engine.audit_pages()
    # post-retirement traffic routes over the survivors only
    more = _workload(2, seed=9)
    for r in more:
        rt.submit(r)
    guard = 0
    while any(r.outcome is None for r in more):
        rt.step()
        guard += 1
        assert guard < 3000
    assert all(r.outcome.ok for r in more)
    assert rt.replicas[2].steps < rt.steps   # tombstone never stepped


@pytest.mark.slow    # live decode (~5s of compiles); see the 870s-wall
def test_drain_requeues_do_not_charge_budget(model):
    # note above — re-gated per CI run by stage_unit + elasticsmoke
    """Drain-time re-queues are the router's doing: max_requeues=0
    still finishes every request (a charged re-queue would terminate
    FAILED_REPLICA immediately)."""
    rt = _fleet(model, n=2, max_requeues=0)
    reqs = _workload(4)
    for r in reqs:
        rt.submit(r)
    rt.step()
    rt.remove_replica(1)
    guard = 0
    while any(r.outcome is None for r in reqs):
        rt.step()
        guard += 1
        assert guard < 3000
    assert all(r.outcome is not None and r.outcome.ok for r in reqs), \
        [r.outcome.value for r in reqs]


# --------------------------------------------------------------------- #
# honest Retry-After through the frontend while membership churns
# --------------------------------------------------------------------- #

def test_shed_during_transition_carries_retry_after(model):
    """Router-level half of the satellite: a shed recorded while a
    replica is mid-transition carries the fleet retry hint."""
    rt = _fleet(model, n=2, max_queue=1)
    rt.remove_replica(1)                 # transition in progress
    assert rt.replicas[1].state is ReplicaState.DRAINING
    reqs = _workload(6)
    shed = [r for r in reqs if not rt.submit(r)]
    assert shed, "expected sheds past the depth-1 router queue"
    for r in shed:
        assert r.outcome is Outcome.SHED
        assert r.retry_after_s is not None and r.retry_after_s > 0


@pytest.mark.slow    # live HTTP streams over a decoding fleet (~3s);
def test_frontend_surfaces_retry_after_across_scale_down(model):
    # see the 870s-wall note above — ci stage_unit runs it every time
    """One stable HTTP endpoint while membership churns underneath:
    scale the fleet down before traffic, saturate the survivor, and
    the 429 must carry a real Retry-After header round-tripped from
    the fleet retry hint."""
    import threading
    import time as _time
    from incubator_mxnet_tpu.serve import ServeFrontend
    from incubator_mxnet_tpu.serve.frontend import (http_request,
                                                    stream_completion)
    rt = _fleet(model, n=2, max_queue=8)
    rt.remove_replica(1)                 # churn before the endpoint
    with ServeFrontend(rt) as fe:        # opens — the driven steps
        holds = []                       # finalise the retirement

        def long_stream():
            holds.append(stream_completion(
                "127.0.0.1", fe.bound_port,
                {"prompt": [2, 3, 4], "max_new_tokens": 48}))

        threads = [threading.Thread(target=long_stream, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < 30:
            if rt.replicas[1].state is ReplicaState.RETIRED and \
                    len(rt._inflight) >= 2:
                break
            _time.sleep(0.01)
        assert rt.replicas[1].state is ReplicaState.RETIRED
        # squeeze the admission bound shut so the probe sheds
        # DETERMINISTICALLY — the point under test is the honest
        # Retry-After on the refusal, not the exact saturation shape
        rt.max_queue = 0
        status, headers, body = http_request(
            "127.0.0.1", fe.bound_port, "POST", "/v1/completions",
            {"prompt": [5, 6], "max_new_tokens": 4, "stream": False})
        rt.max_queue = 8
        assert status == 429
        assert body["outcome"] == "SHED"
        assert "retry-after" in headers
        assert int(headers["retry-after"]) >= 1
        assert body["retry_after_s"] > 0
        for t in threads:
            t.join(timeout=60)
        assert all(h["final"]["outcome"] == "MAX_TOKENS"
                   for h in holds)
    assert rt.scale_downs == 1


# --------------------------------------------------------------------- #
# FleetSupervisor policy units (fake router — pure host-side)
# --------------------------------------------------------------------- #

class _FakeRep:
    def __init__(self, idx, state=ReplicaState.SERVING):
        self.idx = idx
        self.state = state
        self.role = "mixed"


class _FakeRouter:
    """Just enough Router surface for the supervisor's policy loop."""

    def __init__(self, n=2):
        self.replicas = [_FakeRep(i) for i in range(n)]
        self.replica_deaths = 0
        self.log = []
        self.flight = False
        self.calls = []
        self.queue_depth = 0
        self.busy = False
        self.brownout = 0

    def add_replica(self, engine, role="mixed"):
        idx = len(self.replicas)
        self.replicas.append(_FakeRep(idx, ReplicaState.WARMING))
        self.calls.append(("add", idx))
        return idx

    def remove_replica(self, idx):
        self.replicas[idx].state = ReplicaState.DRAINING
        self.calls.append(("remove", idx))
        return {"migrated": 0, "requeued": 0, "remaining": 0}

    def upgrade_replica(self, idx, params=None, manager=None,
                        step=None):
        self.replicas[idx].state = ReplicaState.DRAINING
        self.calls.append(("upgrade", idx))
        return {"migrated": 0, "requeued": 0, "remaining": 0}

    def health_snapshot(self):
        live = [r for r in self.replicas
                if r.state not in (ReplicaState.DEAD,
                                   ReplicaState.RETIRED)]
        return {
            "queue_depth": self.queue_depth,
            "inflight": int(self.busy),
            "fleet_size": len(live),
            "replicas": [
                {"idx": r.idx, "state": r.state.value,
                 "engine": {"brownout_level": self.brownout,
                            "free_slots": 0 if self.busy else 2,
                            "queue_depth": 0,
                            "active_slots": 2 if self.busy else 0}}
                for r in self.replicas
                if r.state not in (ReplicaState.DEAD,
                                   ReplicaState.RETIRED)],
        }


def _fake_sup(rt, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_steps", 3)
    kw.setdefault("down_steps", 5)
    return FleetSupervisor(rt, spawn=lambda: object(), recorder=False,
                           **kw)


def test_supervisor_scales_up_after_sustained_pressure():
    rt = _FakeRouter(2)
    sup = _fake_sup(rt)
    rt.queue_depth, rt.busy = 3, True    # pressured
    sup.tick()
    sup.tick()
    assert not rt.calls                  # dwell: not yet
    sup.tick()
    assert rt.calls == [("add", 2)]      # 3rd consecutive tick fires
    sup.tick()                           # WARMING blocks a 2nd spawn
    assert rt.calls == [("add", 2)]
    rt.replicas[2].state = ReplicaState.SERVING
    rt.queue_depth, rt.busy = 0, False   # pressure gone: counter resets
    sup.tick()
    rt.queue_depth, rt.busy = 3, True
    sup.tick()
    sup.tick()
    assert len(rt.calls) == 1            # dwell restarted from zero


def test_supervisor_scale_up_respects_max_replicas():
    rt = _FakeRouter(2)
    sup = _fake_sup(rt, max_replicas=2)
    rt.queue_depth, rt.busy = 5, True
    for _ in range(10):
        sup.tick()
    assert not rt.calls


def test_supervisor_scales_down_after_sustained_idle():
    rt = _FakeRouter(3)
    sup = _fake_sup(rt, down_steps=5)
    for _ in range(4):
        sup.tick()
    assert not rt.calls
    sup.tick()
    assert rt.calls == [("remove", 2)]   # newest SERVING retires
    rt.replicas[2].state = ReplicaState.RETIRED
    for _ in range(10):
        sup.tick()
    # min_replicas=1 allows one more, after a fresh dwell
    assert rt.calls == [("remove", 2), ("remove", 1)]
    rt.replicas[1].state = ReplicaState.RETIRED
    for _ in range(10):
        sup.tick()
    assert len(rt.calls) == 2            # never below min_replicas


def test_supervisor_replaces_deaths_and_respects_max():
    rt = _FakeRouter(2)
    sup = _fake_sup(rt, max_replicas=2)
    rt.replicas[0].state = ReplicaState.DEAD
    rt.replica_deaths = 1
    sup.tick()
    assert rt.calls == [("add", 2)]      # replacement fits under max
    assert sup.replacements == 1
    rt.replicas[1].state = ReplicaState.DEAD
    rt.replica_deaths = 2
    rt.replicas[2].state = ReplicaState.SERVING
    sup.tick()
    assert len(rt.calls) == 2 and sup.replacements == 2
    assert sup.snapshot()["replacements"] == 2


def test_supervisor_roll_walks_fleet_and_halts_when_degraded():
    rt = _FakeRouter(3)
    sup = _fake_sup(rt)
    sup.start_upgrade(params={"0": np.zeros((1,), np.float32)})
    with pytest.raises(MXNetError, match="one roll"):
        sup.start_upgrade(params={})
    sup.tick()
    assert rt.calls == [("upgrade", 0)]
    sup.tick()                           # replica 0 still DRAINING
    assert len(rt.calls) == 1
    rt.replicas[0].state = ReplicaState.SERVING
    rt.replicas[1].state = ReplicaState.DEGRADED
    sup.tick()                           # degraded fleet: roll halts
    assert len(rt.calls) == 1
    assert sup.snapshot()["roll"]["halted"]
    rt.replicas[1].state = ReplicaState.SERVING
    sup.tick()                           # resumed
    assert rt.calls[-1] == ("upgrade", 1)
    rt.replicas[1].state = ReplicaState.SERVING
    sup.tick()
    assert rt.calls[-1] == ("upgrade", 2)
    rt.replicas[2].state = ReplicaState.SERVING
    sup.tick()
    assert sup.snapshot()["roll"] is None
    assert sup.upgrades_completed == 1


# --------------------------------------------------------------------- #
# the race matrix (live fleets — slow; elasticsmoke reruns these
# shapes end-to-end every CI run)
# --------------------------------------------------------------------- #

@pytest.mark.slow
def test_race_add_during_drain(model):
    base = _baseline(model, 10)
    rt = _fleet(model, n=3)
    reqs = _workload(10)
    inj = ScaleDownRace(victim=2, spawn=lambda: _engine(model),
                        at_step=2)
    run_fleet_chaos(rt, reqs, [inj])
    assert inj.fired and inj.added == 3
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    for i, r in enumerate(reqs):
        assert list(r.token_ids) == base[i]
    assert_fleet_health_consistent(rt, reqs)
    for _ in range(4):
        rt.step()                        # finalise the drain
    assert rt.replicas[2].state is ReplicaState.RETIRED
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD:
            rep.engine.audit_pages()


@pytest.mark.slow
def test_race_remove_during_kill_failover(model):
    """A replica dies; while its requests replay, another replica is
    removed — the failover re-queues and the drain migrations must
    not double-finish or lose anything."""
    base = _baseline(model, 10)
    rt = _fleet(model, n=3, max_requeues=3)
    reqs = _workload(10)
    kill = KillReplica(replica=0, at_step=3, phase="decode")
    drain = DrainKill(victim=1, at_step=4, kill_after=10 ** 6)
    # kill_after never fires: this instance only drives the remove
    run_fleet_chaos(rt, reqs, [kill, drain])
    assert kill.fired and drain.removed_at is not None
    assert all(r.outcome is not None for r in reqs)
    ok = [r for r in reqs if r.outcome.ok]
    for i, r in enumerate(reqs):
        if r.outcome.ok:
            assert list(r.token_ids) == base[i]
        else:                            # bounded structured give-up
            assert r.outcome in (Outcome.FAILED_REPLICA,)
            assert r.retry_after_s is not None
    assert len(ok) >= 8
    assert_fleet_health_consistent(rt, reqs)
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD and rep.killed is None:
            rep.engine.audit_pages()


@pytest.mark.slow
def test_race_death_mid_drain(model):
    base = _baseline(model, 10)
    rt = _fleet(model, n=3, max_requeues=3)
    reqs = _workload(10)
    inj = DrainKill(victim=2, at_step=2, kill_after=1)
    run_fleet_chaos(rt, reqs, [inj])
    assert inj.fired
    assert all(r.outcome is not None for r in reqs)
    for i, r in enumerate(reqs):
        if r.outcome.ok:
            assert list(r.token_ids) == base[i]
    assert_fleet_health_consistent(rt, reqs)
    if inj.killed_mid_drain:
        # DEAD wins over RETIRED: the drain must never finalise
        assert rt.replicas[2].state is ReplicaState.DEAD
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD and rep.killed is None:
            rep.engine.audit_pages()


@pytest.mark.slow
def test_race_cancel_vs_migrate_vs_retire(model):
    """Cancel a request that the retirement drain is migrating —
    whichever transition wins, exactly one CANCELLED-or-ok terminal,
    never two."""
    rt = _fleet(model, n=2)
    reqs = _workload(8)
    cancelled = []

    def before(router, i):
        if i == 2:
            router.remove_replica(1)
        if i == 3:
            for t in list(router._inflight):
                if router.cancel(t.client, detail="race cancel"):
                    cancelled.append(t.client)
                break

    rt.run(reqs, before_step=before)
    assert all(r.outcome is not None for r in reqs)
    assert cancelled, "the cancel should land at step 3"
    for r in cancelled:
        assert r.outcome is Outcome.CANCELLED
    assert_fleet_health_consistent(rt, reqs)
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD:
            rep.engine.audit_pages()


# --------------------------------------------------------------------- #
# supervisor integration on a live fleet (slow)
# --------------------------------------------------------------------- #

@pytest.mark.slow
def test_supervisor_grows_under_load_and_upgrade_roll_is_lossless(model):
    rt = _fleet(model, n=2)
    sup = FleetSupervisor(rt, spawn=lambda: _engine(model),
                          min_replicas=1, max_replicas=3, up_steps=2,
                          down_steps=10 ** 6)
    reqs = _workload(16)
    for r in reqs:
        rt.submit(r)
    guard = 0
    while any(r.outcome is None for r in reqs):
        rt.step()
        sup.tick()
        guard += 1
        assert guard < 5000
    assert all(r.outcome.ok for r in reqs)
    assert sup.scale_ups >= 1 and rt.scale_ups == sup.scale_ups
    # same-weights rolling upgrade under fresh load: zero losses,
    # parity with the pre-upgrade streams
    params = _same_params(rt)
    sup.start_upgrade(params=params)
    reqs2 = _workload(10, seed=5)
    for r in reqs2:
        rt.submit(r)
    guard = 0
    while any(r.outcome is None for r in reqs2) or \
            sup.snapshot()["roll"] is not None:
        rt.step()
        sup.tick()
        guard += 1
        assert guard < 8000
    assert all(r.outcome is not None and r.outcome.ok for r in reqs2)
    assert sup.upgrades_completed == 1
    assert rt.upgrades >= 2              # every live replica swapped
    control = _fleet(model)
    creqs = _workload(10, seed=5)
    control.run(creqs)
    for a, b in zip(reqs2, creqs):
        assert list(a.token_ids) == list(b.token_ids)
    for rep in rt.replicas:
        if rep.state not in (ReplicaState.DEAD, ReplicaState.RETIRED):
            rep.engine.audit_pages()


@pytest.mark.slow
def test_supervisor_killed_mid_upgrade_cannot_wedge(model):
    """The tentpole chaos claim: the roll's in-flight replica is
    finalised by the ROUTER'S step loop even after the supervisor
    stops ticking forever."""
    base = _baseline(model, 10)
    rt = _fleet(model, n=2)
    sup = FleetSupervisor(rt, spawn=lambda: _engine(model),
                          min_replicas=1, max_replicas=3,
                          up_steps=10 ** 6, down_steps=10 ** 6)
    inj = SupervisorChaos(sup, upgrade_at=2, kill_at=4,
                          upgrade_src={"params": _same_params(rt)})
    reqs = _workload(10)
    run_fleet_chaos(rt, reqs, [inj])
    assert inj.upgrade_started and inj.killed_at_step == 4
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    for i, r in enumerate(reqs):
        assert list(r.token_ids) == base[i]
    assert_fleet_health_consistent(rt, reqs)
    # no replica stranded DRAINING: the router finalised whatever the
    # dead supervisor left mid-swap
    for _ in range(6):
        rt.step()
    assert not any(r.state is ReplicaState.DRAINING
                   for r in rt.replicas)
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD:
            rep.engine.audit_pages()
