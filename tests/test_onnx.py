"""ONNX converter tests (VERDICT r2 missing #4 / next-round #10).

The graph-translation layer (graph_to_ir / ir_to_symbol) is exercised
without the onnx wheel: a LeNet symbol round-trips through the ONNX IR
and must produce identical outputs. Proto serialization runs
UNCONDITIONALLY through the vendored wire-format layer
(contrib/_onnx_proto.py) — no onnx-package gate remains."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib import onnx as onnx_mod


def _lenet_symbol():
    d = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(d, mx.sym.Variable("c1w"),
                            mx.sym.Variable("c1b"), kernel=(5, 5),
                            num_filter=6, pad=(2, 2), name="c1")
    a1 = mx.sym.Activation(c1, act_type="tanh", name="a1")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", name="p1")
    f = mx.sym.flatten(p1, name="flat")
    fc1 = mx.sym.FullyConnected(f, mx.sym.Variable("f1w"),
                                mx.sym.Variable("f1b"), num_hidden=32,
                                flatten=False, name="fc1")
    a2 = mx.sym.Activation(fc1, act_type="relu", name="a2")
    fc2 = mx.sym.FullyConnected(a2, mx.sym.Variable("f2w"),
                                mx.sym.Variable("f2b"), num_hidden=10,
                                flatten=False, name="fc2")
    return mx.sym.softmax(fc2, name="sm")


def _lenet_params(rng):
    return {
        "c1w": nd.array(rng.randn(6, 1, 5, 5).astype(np.float32) * 0.1),
        "c1b": nd.array(np.zeros(6, np.float32)),
        "f1w": nd.array(rng.randn(32, 6 * 14 * 14).astype(np.float32)
                        * 0.01),
        "f1b": nd.array(np.zeros(32, np.float32)),
        "f2w": nd.array(rng.randn(10, 32).astype(np.float32) * 0.1),
        "f2b": nd.array(np.zeros(10, np.float32)),
    }


def test_graph_to_ir_lenet_structure():
    sym = _lenet_symbol()
    rng = np.random.RandomState(0)
    ir = onnx_mod.graph_to_ir(sym, _lenet_params(rng),
                              {"data": (1, 1, 28, 28)})
    ops = [n["op_type"] for n in ir["nodes"]]
    assert ops == ["Conv", "Tanh", "MaxPool", "Flatten", "Gemm", "Relu",
                   "Gemm", "Softmax"]
    assert [i["name"] for i in ir["inputs"]] == ["data"]
    assert set(ir["initializers"]) == {"c1w", "c1b", "f1w", "f1b",
                                       "f2w", "f2b"}
    conv = ir["nodes"][0]
    assert conv["attrs"]["kernel_shape"] == [5, 5]
    assert conv["attrs"]["pads"] == [2, 2, 2, 2]
    gemm = ir["nodes"][4]
    assert gemm["attrs"]["transB"] == 1


def test_ir_round_trip_outputs_match():
    """LeNet → ONNX IR → symbol: outputs must be bit-comparable."""
    sym = _lenet_symbol()
    rng = np.random.RandomState(1)
    params = _lenet_params(rng)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)

    want = sym.eval(data=nd.array(x), **params)[0].asnumpy()

    ir = onnx_mod.graph_to_ir(sym, params, {"data": (2, 1, 28, 28)})
    sym2, arg_params = onnx_mod.ir_to_symbol(
        ir["nodes"], ir["inputs"], ir["initializers"])
    got = sym2.eval(data=nd.array(x), **arg_params)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_unsupported_op_raises():
    d = mx.sym.Variable("data")
    s = mx.sym.topk(d, k=2)
    with pytest.raises(mx.MXNetError, match="unsupported op"):
        onnx_mod.graph_to_ir(s, {}, {"data": (2, 4)})


def test_proto_file_roundtrip_outputs_match(tmp_path):
    """export_model -> .onnx bytes -> import_model, UNCONDITIONAL: the
    vendored wire-format layer (_onnx_proto.py) removes the onnx-wheel
    gate (VERDICT r3 next-round #8). When the real onnx package is
    present, export additionally runs onnx.checker — same test either
    way."""
    sym = _lenet_symbol()
    rng = np.random.RandomState(2)
    params = _lenet_params(rng)
    f = onnx_mod.export_model(sym, params, {"data": (1, 1, 28, 28)},
                              str(tmp_path / "m.onnx"))
    sym2, arg_params, _ = onnx_mod.import_model(f)
    x = rng.randn(1, 1, 28, 28).astype(np.float32)
    want = sym.eval(data=nd.array(x), **params)[0].asnumpy()
    got = sym2.eval(data=nd.array(x), **arg_params)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_vendored_proto_primitives():
    """Wire-level checks of the vendored protobuf layer: varint edge
    cases (negative int64 two's-complement), tensor raw_data round-trip,
    attribute typing (INT / FLOAT / INTS / STRING)."""
    from incubator_mxnet_tpu.contrib import _onnx_proto as op

    # tensors: f32 and int64, any shape
    for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                np.array([-3, 0, 7], np.int64)):
        name, back = op.parse_tensor(op.tensor_bytes("t", arr))
        assert name == "t" and back.dtype == arr.dtype
        np.testing.assert_array_equal(back, arr)

    # node with every attribute kind the converter emits
    nb = op.node_bytes("Conv", ["x", "w"], ["y"], name="conv0",
                       attrs={"group": 1, "epsilon": 0.5,
                              "kernel_shape": [5, 5], "pad_mode": "VALID",
                              "neg": -2})
    node = op.parse_node(nb)
    assert node["op_type"] == "Conv" and node["name"] == "conv0"
    assert node["inputs"] == ["x", "w"] and node["outputs"] == ["y"]
    a = node["attrs"]
    assert a["group"] == 1 and a["neg"] == -2
    assert abs(a["epsilon"] - 0.5) < 1e-7
    assert a["kernel_shape"] == [5, 5]
    assert a["pad_mode"] == b"VALID"  # bytes, like onnx.helper

    # value_info shape round-trip incl. the shapeless (None) form
    vi = op.parse_value_info(op.value_info_bytes("in0", op.FLOAT,
                                                 (1, 3, 8, 8)))
    assert vi == {"name": "in0", "shape": [1, 3, 8, 8]}
    vi2 = op.parse_value_info(op.value_info_bytes("out0", op.FLOAT, None))
    assert vi2["name"] == "out0"
